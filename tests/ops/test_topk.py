import jax
import jax.numpy as jnp
import numpy as np

from dgmc_tpu.ops import chunked_topk, dense_topk


def test_chunked_matches_dense():
    key = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(key)
    h_s = jax.random.normal(k1, (2, 17, 8))
    h_t = jax.random.normal(k2, (2, 53, 8))
    for k in (1, 5, 10):
        idx_d = dense_topk(h_s, h_t, k)
        idx_c = chunked_topk(h_s, h_t, k, block=16)
        np.testing.assert_array_equal(idx_d, idx_c)


def test_chunked_matches_dense_with_mask():
    key = jax.random.PRNGKey(1)
    k1, k2, k3 = jax.random.split(key, 3)
    h_s = jax.random.normal(k1, (3, 9, 4))
    h_t = jax.random.normal(k2, (3, 31, 4))
    t_mask = jax.random.bernoulli(k3, 0.7, (3, 31))
    idx_d = dense_topk(h_s, h_t, 4, t_mask=t_mask)
    idx_c = chunked_topk(h_s, h_t, 4, t_mask=t_mask, block=8)
    np.testing.assert_array_equal(idx_d, idx_c)


def test_tie_breaking_prefers_lower_index():
    # All-equal scores: top-k must pick the lowest target indices, in order,
    # in both implementations.
    h_s = jnp.ones((1, 3, 2))
    h_t = jnp.ones((1, 20, 2))
    idx_d = dense_topk(h_s, h_t, 4)
    idx_c = chunked_topk(h_s, h_t, 4, block=4)
    np.testing.assert_array_equal(idx_d, np.tile(np.arange(4), (1, 3, 1)))
    np.testing.assert_array_equal(idx_c, idx_d)


def test_auto_gate_resolved_per_call_not_cached(monkeypatch):
    """The pallas auto-dispatch decision must be re-read on every call: a
    jitted wrapper would bake the trace-time contextvar into a cached jaxpr
    and never consult disable_fused_kernels() again (the nested-jit cache
    ignores contextvars)."""
    from dgmc_tpu.ops.pallas import dispatch

    calls = []
    real = dispatch.fused_kernels_allowed

    def counting():
        calls.append(True)
        return real()

    monkeypatch.setattr(dispatch, 'fused_kernels_allowed', counting)
    h_s = jnp.ones((1, 4, 2))
    h_t = jnp.ones((1, 8, 2))
    chunked_topk(h_s, h_t, 2, block=4)
    chunked_topk(h_s, h_t, 2, block=4)  # same shapes: jit cache hit inside
    assert len(calls) == 2


def test_streamed_matches_chunked_bit_identical():
    """Source-chunk streaming (streamed_topk) returns bit-identical
    indices AND values to the unstreamed scan — rows are independent, so
    chunking the source axis is pure scheduling (ragged chunk included)."""
    from dgmc_tpu.ops.topk import streamed_topk
    key = jax.random.PRNGKey(4)
    k1, k2, k3 = jax.random.split(key, 3)
    h_s = jax.random.normal(k1, (2, 37, 8))
    h_t = jax.random.normal(k2, (2, 53, 8))
    t_mask = jax.random.bernoulli(k3, 0.8, (2, 53))
    va, ia = chunked_topk(h_s, h_t, 5, t_mask=t_mask, block=16,
                          pallas=False, return_values=True)
    vb, ib = streamed_topk(h_s, h_t, 5, 8, t_mask=t_mask, block=16,
                           pallas=False, return_values=True)
    np.testing.assert_array_equal(np.asarray(ia), np.asarray(ib))
    np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))


def test_tile_extractor_forms_identical():
    """The backend-conditional per-tile extractors — one lax.top_k sort
    pass (CPU) vs k rounds of argmax+mask (TPU) — are bit-identical,
    duplicate scores and masked columns included, so the r7 cost-model
    inversion swaps them freely."""
    import dgmc_tpu.ops.topk as T
    rng = np.random.RandomState(3)
    h_s = jnp.asarray(rng.randn(2, 19, 8).astype(np.float32))
    base = rng.randn(2, 16, 8).astype(np.float32)
    # Duplicated target rows force score ties across tiles.
    h_t = jnp.asarray(np.concatenate([base, base], axis=1))
    tm = jnp.asarray(rng.rand(2, 32) > 0.3)
    old = T.TILE_SORT
    try:
        T.TILE_SORT = True
        a = np.asarray(T.chunked_topk(h_s, h_t, 6, t_mask=tm, block=8,
                                      pallas=False))
        s = np.asarray(T.streamed_topk(h_s, h_t, 6, 4, t_mask=tm, block=8,
                                       pallas=False))
        T.TILE_SORT = False
        b = np.asarray(T.chunked_topk(h_s, h_t, 6, t_mask=tm, block=8,
                                      pallas=False))
    finally:
        T.TILE_SORT = old
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(a, s)
    np.testing.assert_array_equal(
        a, np.asarray(dense_topk(h_s, h_t, 6, t_mask=tm)))


def test_double_buffered_stream_matches_serial_reference():
    """The double-buffered chunk pipeline (prefetched-carry scan) is
    bit-identical to the retired single-buffered formulation — scan
    straight over the chunk stack — on ties, masked targets, a ragged
    final chunk, and BOTH per-tile extractor forms. The pipeline only
    reorders data movement; if it ever touches values or tie order,
    this is the test that says so."""
    import functools

    import dgmc_tpu.ops.topk as T

    def serial_streamed(h_s, h_t, k, chunk, t_mask, block, sort_tiles):
        # The pre-pipeline loop, verbatim semantics: fetch chunk k,
        # THEN score chunk k — the xs slice feeds the compute directly.
        B, N_s, C = h_s.shape
        pad = (-N_s) % chunk
        if pad:
            h_s = jnp.pad(h_s, ((0, 0), (0, pad), (0, 0)))
        n_chunks = h_s.shape[1] // chunk
        chunks = h_s.reshape(B, n_chunks, chunk, C).transpose(1, 0, 2, 3)

        def body(_, h_chunk):
            return None, T._chunked_topk(h_chunk, h_t, k, t_mask, block,
                                         True, False, sort_tiles)

        _, (vals, idx) = jax.lax.scan(body, None, chunks)
        merge = functools.partial(
            lambda a: a.transpose(1, 0, 2, 3).reshape(
                B, n_chunks * chunk, k)[:, :N_s])
        return merge(vals), merge(idx)

    rng = np.random.RandomState(7)
    base = rng.randn(1, 16, 8).astype(np.float32)
    h_t = jnp.asarray(np.concatenate([base, base], axis=1))  # forced ties
    # ragged final chunk: 37 % 8 != 0
    h_s = jnp.asarray(rng.randn(1, 37, 8).astype(np.float32))
    tm = jnp.asarray(rng.rand(1, 32) > 0.4)
    for sort_tiles in (True, False):
        sv, si = serial_streamed(h_s, h_t, 5, 8, tm, 8, sort_tiles)
        dv, di = T._streamed_topk(h_s, h_t, 5, tm, 8, 8, True, False,
                                  sort_tiles)
        np.testing.assert_array_equal(np.asarray(si), np.asarray(di))
        np.testing.assert_array_equal(np.asarray(sv), np.asarray(dv))


def test_double_buffered_carry_holds_prefetched_chunk():
    """The pipeline's structural claim, pinned at the jaxpr level: the
    chunk scan CARRIES a ``[B, chunk, C]`` buffer (the prefetched
    slot), and the per-iteration fetch (``dynamic_slice`` off the loop
    counter) produces ONLY that carry — it never feeds this
    iteration's compute, which consumes the slot fetched one
    iteration earlier. The serial form had no chunk-shaped carry at
    all (its xs slice fed the compute directly — the SCH403 shape the
    rewrite retires; the golden HLO fixtures in
    tests/analysis/test_sched_rules.py pin the rule itself, since a
    fused CPU build hides the slice from compiled-text checks)."""
    import dgmc_tpu.ops.topk as T
    B, chunk, C = 1, 16, 8
    h_s = jnp.zeros((B, 64, C), jnp.float32)
    h_t = jnp.zeros((B, 32, C), jnp.float32)
    jaxpr = jax.make_jaxpr(
        lambda a, b: T._streamed_topk(a, b, 4, None, chunk, 8, False,
                                      False, True))(h_s, h_t)

    def find_scans(jpr, out):
        for eqn in jpr.eqns:
            if eqn.primitive.name == 'scan':
                out.append(eqn)
            for v in eqn.params.values():
                if hasattr(v, 'jaxpr'):
                    find_scans(v.jaxpr, out)
        return out

    scans = find_scans(jaxpr.jaxpr, [])

    def carry_vars(e):
        start = e.params.get('num_consts', 0)
        return e.invars[start:start + e.params['num_carry']]

    chunk_scans = [
        e for e in scans
        if any(getattr(v.aval, 'shape', None) == (B, chunk, C)
               for v in carry_vars(e))]
    carries = [[getattr(v.aval, 'shape', None) for v in carry_vars(e)]
               for e in scans]
    assert chunk_scans, (
        f'no scan carries the [B, chunk, C] prefetch slot: {carries}')
    body = chunk_scans[0].params['jaxpr'].jaxpr
    # The fetch: a dynamic_slice whose descendants inside the body are
    # pure bookkeeping ending at the carry output — NEVER this
    # iteration's compute (the search call / einsum consume the slot
    # fetched one iteration earlier, via the carry input).
    ds = [e for e in body.eqns if e.primitive.name == 'dynamic_slice']
    assert ds, [e.primitive.name for e in body.eqns]
    fetched = set()
    for e in ds:
        fetched.update(id(v) for v in e.outvars)
    compute_consumers = []
    for e in body.eqns:
        if any(id(v) in fetched for v in e.invars):
            if e.primitive.name in ('squeeze', 'reshape', 'broadcast_in_dim'):
                fetched.update(id(v) for v in e.outvars)
            else:
                compute_consumers.append(e.primitive.name)
    assert compute_consumers == [], (
        f'prefetched chunk consumed by in-body compute: '
        f'{compute_consumers}')
    # ... and the carry slot written back IS fetch-derived.
    carry_out = body.outvars[:chunk_scans[0].params['num_carry']]
    assert any(id(v) in fetched for v in carry_out), (
        'carry slot is not the fetched chunk')
