import jax
import jax.numpy as jnp
import numpy as np

from dgmc_tpu.ops import chunked_topk, dense_topk


def test_chunked_matches_dense():
    key = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(key)
    h_s = jax.random.normal(k1, (2, 17, 8))
    h_t = jax.random.normal(k2, (2, 53, 8))
    for k in (1, 5, 10):
        idx_d = dense_topk(h_s, h_t, k)
        idx_c = chunked_topk(h_s, h_t, k, block=16)
        np.testing.assert_array_equal(idx_d, idx_c)


def test_chunked_matches_dense_with_mask():
    key = jax.random.PRNGKey(1)
    k1, k2, k3 = jax.random.split(key, 3)
    h_s = jax.random.normal(k1, (3, 9, 4))
    h_t = jax.random.normal(k2, (3, 31, 4))
    t_mask = jax.random.bernoulli(k3, 0.7, (3, 31))
    idx_d = dense_topk(h_s, h_t, 4, t_mask=t_mask)
    idx_c = chunked_topk(h_s, h_t, 4, t_mask=t_mask, block=8)
    np.testing.assert_array_equal(idx_d, idx_c)


def test_tie_breaking_prefers_lower_index():
    # All-equal scores: top-k must pick the lowest target indices, in order,
    # in both implementations.
    h_s = jnp.ones((1, 3, 2))
    h_t = jnp.ones((1, 20, 2))
    idx_d = dense_topk(h_s, h_t, 4)
    idx_c = chunked_topk(h_s, h_t, 4, block=4)
    np.testing.assert_array_equal(idx_d, np.tile(np.arange(4), (1, 3, 1)))
    np.testing.assert_array_equal(idx_c, idx_d)


def test_auto_gate_resolved_per_call_not_cached(monkeypatch):
    """The pallas auto-dispatch decision must be re-read on every call: a
    jitted wrapper would bake the trace-time contextvar into a cached jaxpr
    and never consult disable_fused_kernels() again (the nested-jit cache
    ignores contextvars)."""
    from dgmc_tpu.ops.pallas import dispatch

    calls = []
    real = dispatch.fused_kernels_allowed

    def counting():
        calls.append(True)
        return real()

    monkeypatch.setattr(dispatch, 'fused_kernels_allowed', counting)
    h_s = jnp.ones((1, 4, 2))
    h_t = jnp.ones((1, 8, 2))
    chunked_topk(h_s, h_t, 2, block=4)
    chunked_topk(h_s, h_t, 2, block=4)  # same shapes: jit cache hit inside
    assert len(calls) == 2


def test_streamed_matches_chunked_bit_identical():
    """Source-chunk streaming (streamed_topk) returns bit-identical
    indices AND values to the unstreamed scan — rows are independent, so
    chunking the source axis is pure scheduling (ragged chunk included)."""
    from dgmc_tpu.ops.topk import streamed_topk
    key = jax.random.PRNGKey(4)
    k1, k2, k3 = jax.random.split(key, 3)
    h_s = jax.random.normal(k1, (2, 37, 8))
    h_t = jax.random.normal(k2, (2, 53, 8))
    t_mask = jax.random.bernoulli(k3, 0.8, (2, 53))
    va, ia = chunked_topk(h_s, h_t, 5, t_mask=t_mask, block=16,
                          pallas=False, return_values=True)
    vb, ib = streamed_topk(h_s, h_t, 5, 8, t_mask=t_mask, block=16,
                           pallas=False, return_values=True)
    np.testing.assert_array_equal(np.asarray(ia), np.asarray(ib))
    np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))


def test_tile_extractor_forms_identical():
    """The backend-conditional per-tile extractors — one lax.top_k sort
    pass (CPU) vs k rounds of argmax+mask (TPU) — are bit-identical,
    duplicate scores and masked columns included, so the r7 cost-model
    inversion swaps them freely."""
    import dgmc_tpu.ops.topk as T
    rng = np.random.RandomState(3)
    h_s = jnp.asarray(rng.randn(2, 19, 8).astype(np.float32))
    base = rng.randn(2, 16, 8).astype(np.float32)
    # Duplicated target rows force score ties across tiles.
    h_t = jnp.asarray(np.concatenate([base, base], axis=1))
    tm = jnp.asarray(rng.rand(2, 32) > 0.3)
    old = T.TILE_SORT
    try:
        T.TILE_SORT = True
        a = np.asarray(T.chunked_topk(h_s, h_t, 6, t_mask=tm, block=8,
                                      pallas=False))
        s = np.asarray(T.streamed_topk(h_s, h_t, 6, 4, t_mask=tm, block=8,
                                       pallas=False))
        T.TILE_SORT = False
        b = np.asarray(T.chunked_topk(h_s, h_t, 6, t_mask=tm, block=8,
                                      pallas=False))
    finally:
        T.TILE_SORT = old
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(a, s)
    np.testing.assert_array_equal(
        a, np.asarray(dense_topk(h_s, h_t, 6, t_mask=tm)))
