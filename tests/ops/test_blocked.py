"""Blocked (scatter-free) adjacency aggregation — correctness gates.

The blocked path (``dgmc_tpu/ops/blocked.py``) must match the plain
gather/scatter formulation exactly (up to f32 summation order): forward
values, gradients, degree normalization, hub-heavy graphs that force
multiple blocks per node range, and the full DGMC forward in both dense
and sparse variants, including the explicit ``batch_pair`` union.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dgmc_tpu.models import DGMC, RelCNN
from dgmc_tpu.ops import GraphBatch
from dgmc_tpu.ops.blocked import (adj_matmul, attach_blocks,
                                  build_edge_blocks)


def random_graph(rng, b, n, e, c, hub=False):
    senders = rng.randint(0, n, (b, e)).astype(np.int32)
    receivers = rng.randint(0, n, (b, e)).astype(np.int32)
    if hub:  # one node receives half of all edges: many blocks, one range
        receivers[0, :e // 2] = 3
    return GraphBatch(
        x=rng.randn(b, n, c).astype(np.float32),
        senders=senders, receivers=receivers,
        node_mask=np.ones((b, n), bool),
        edge_mask=rng.rand(b, e) > 0.15,
        edge_attr=None)


def dense_reference(g, values):
    """out[b, n] = sum over unmasked edges with receiver n of
    values[b, sender]."""
    B, N, C = values.shape
    out = np.zeros((B, N, C), np.float32)
    for b in range(B):
        for e in range(g.senders.shape[1]):
            if g.edge_mask[b, e]:
                out[b, g.receivers[b, e]] += np.asarray(
                    values)[b, g.senders[b, e]]
    return out


@pytest.mark.parametrize('hub', [False, True])
def test_adj_matmul_matches_dense_reference(hub):
    rng = np.random.RandomState(0)
    g = random_graph(rng, 2, 200, 1300, 8, hub=hub)
    inc, outg = build_edge_blocks(g.senders, g.receivers, g.edge_mask,
                                  200, rows=32, block_edges=64)
    h = jnp.asarray(g.x)
    got = adj_matmul(h, inc, outg)
    np.testing.assert_allclose(np.asarray(got), dense_reference(g, h),
                               rtol=2e-5, atol=1e-4)


def test_adj_matmul_gradient_is_transpose_aggregation():
    rng = np.random.RandomState(1)
    g = random_graph(rng, 1, 150, 900, 4)
    inc, outg = build_edge_blocks(g.senders, g.receivers, g.edge_mask,
                                  150, rows=32, block_edges=64)
    h = jnp.asarray(g.x)
    w = jnp.asarray(rng.randn(*g.x.shape).astype(np.float32))
    grad = jax.grad(lambda hh: (adj_matmul(hh, inc, outg) * w).sum())(h)
    # d/dh of sum(out*w) aggregates w along the TRANSPOSED adjacency.
    gt = GraphBatch(x=g.x, senders=g.receivers, receivers=g.senders,
                    node_mask=g.node_mask, edge_mask=g.edge_mask,
                    edge_attr=None)
    np.testing.assert_allclose(np.asarray(grad), dense_reference(gt, w),
                               rtol=2e-5, atol=1e-4)


def test_inv_degree_matches_masked_bincount():
    rng = np.random.RandomState(2)
    g = random_graph(rng, 2, 100, 700, 4)
    inc, outg = build_edge_blocks(g.senders, g.receivers, g.edge_mask,
                                  100, rows=32, block_edges=64)
    for blocks, dst in ((inc, g.receivers), (outg, g.senders)):
        deg = np.zeros((2, 100))
        for b in range(2):
            for e in range(700):
                if g.edge_mask[b, e]:
                    deg[b, dst[b, e]] += 1
        np.testing.assert_allclose(np.asarray(blocks.inv_degree)[..., 0],
                                   1.0 / np.maximum(deg, 1.0))


def test_attach_blocks_skips_small_graphs():
    rng = np.random.RandomState(3)
    g = random_graph(rng, 1, 64, 200, 4)
    assert attach_blocks(g).blocks_in is None          # < min_nodes
    assert attach_blocks(g, min_nodes=1).blocks_in is not None


def test_relcnn_blocked_matches_plain():
    rng = np.random.RandomState(4)
    g = random_graph(rng, 2, 600, 3600, 16)
    gb = attach_blocks(g, rows=64, block_edges=128, min_nodes=1,
                       gather_dtype=None)
    psi = RelCNN(16, 32, num_layers=3)
    params = psi.init(jax.random.PRNGKey(0), jnp.asarray(g.x), g)
    out_plain = psi.apply(params, jnp.asarray(g.x), g)
    out_blocked = psi.apply(params, jnp.asarray(gb.x), gb)
    np.testing.assert_allclose(np.asarray(out_plain),
                               np.asarray(out_blocked),
                               rtol=1e-4, atol=1e-4)

    def loss(p, graph):
        return (psi.apply(p, jnp.asarray(graph.x), graph) ** 2).sum()

    g1 = jax.tree_util.tree_leaves(jax.grad(loss)(params, g))
    g2 = jax.tree_util.tree_leaves(jax.grad(loss)(params, gb))
    for v1, v2 in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(v1), np.asarray(v2),
                                   rtol=1e-3, atol=1e-3)


def _pair(rng, blocked, batch_pair=None):
    def mk(n, e):
        g = random_graph(rng, 1, n, e, 24)
        return (attach_blocks(g, rows=64, block_edges=128, min_nodes=1,
                              gather_dtype=None) if blocked else g)
    return mk(300, 1700), mk(400, 2100)


@pytest.mark.parametrize('k', [-1, 10])
def test_dgmc_blocked_matches_plain(k):
    rng = np.random.RandomState(5)
    g_s, g_t = _pair(np.random.RandomState(5), blocked=False)
    gb_s, gb_t = _pair(np.random.RandomState(5), blocked=True)
    del rng
    model = DGMC(RelCNN(24, 48, 2), RelCNN(16, 16, 2), num_steps=2, k=k)
    rngs = {'noise': jax.random.PRNGKey(7),
            'negatives': jax.random.PRNGKey(8)}
    variables = model.init({'params': jax.random.PRNGKey(0), **rngs},
                           g_s, g_t)
    S0_a, SL_a = model.apply(variables, g_s, g_t, rngs=rngs)
    S0_b, SL_b = model.apply(variables, gb_s, gb_t, rngs=rngs)
    np.testing.assert_allclose(np.asarray(S0_a.val), np.asarray(S0_b.val),
                               rtol=5e-4, atol=5e-5)
    np.testing.assert_allclose(np.asarray(SL_a.val), np.asarray(SL_b.val),
                               rtol=5e-4, atol=5e-5)


@pytest.mark.parametrize('k', [-1, 10])
def test_dgmc_batch_pair_union_matches_plain(k):
    g_s, g_t = _pair(np.random.RandomState(6), blocked=False)
    gb_s, gb_t = _pair(np.random.RandomState(6), blocked=True)
    plain = DGMC(RelCNN(24, 48, 2), RelCNN(16, 16, 2), num_steps=2, k=k)
    union = DGMC(RelCNN(24, 48, 2), RelCNN(16, 16, 2), num_steps=2, k=k,
                 batch_pair=True)
    rngs = {'noise': jax.random.PRNGKey(7),
            'negatives': jax.random.PRNGKey(8)}
    variables = plain.init({'params': jax.random.PRNGKey(0), **rngs},
                           g_s, g_t)
    _, SL_a = plain.apply(variables, g_s, g_t, rngs=rngs)
    _, SL_b = union.apply(variables, gb_s, gb_t, rngs=rngs)
    np.testing.assert_allclose(np.asarray(SL_a.val), np.asarray(SL_b.val),
                               rtol=5e-4, atol=5e-5)


@pytest.mark.parametrize('which', ['psi_1', 'psi_2'])
def test_dgmc_batch_pair_single_backbone(which):
    """Per-backbone union granularity: 'psi_1' merges only the feature
    encoder (the once-per-step application whose union stays under the
    gather-efficiency cliff at DBP15K scale), 'psi_2' only the consensus
    net — results must match the plain two-call model either way."""
    g_s, g_t = _pair(np.random.RandomState(6), blocked=False)
    gb_s, gb_t = _pair(np.random.RandomState(6), blocked=True)
    plain = DGMC(RelCNN(24, 48, 2), RelCNN(16, 16, 2), num_steps=2, k=10)
    union = DGMC(RelCNN(24, 48, 2), RelCNN(16, 16, 2), num_steps=2, k=10,
                 batch_pair=which)
    rngs = {'noise': jax.random.PRNGKey(7),
            'negatives': jax.random.PRNGKey(8)}
    variables = plain.init({'params': jax.random.PRNGKey(0), **rngs},
                           g_s, g_t)
    _, SL_a = plain.apply(variables, g_s, g_t, rngs=rngs)
    _, SL_b = union.apply(variables, gb_s, gb_t, rngs=rngs)
    np.testing.assert_allclose(np.asarray(SL_a.val), np.asarray(SL_b.val),
                               rtol=5e-4, atol=5e-5)


def test_dgmc_batch_pair_rejects_unknown_value():
    g_s, g_t = _pair(np.random.RandomState(6), blocked=True)
    model = DGMC(RelCNN(24, 48, 2), RelCNN(16, 16, 2), num_steps=1, k=4,
                 batch_pair='both')
    with pytest.raises(ValueError, match='batch_pair'):
        model.init({'params': jax.random.PRNGKey(0),
                    'noise': jax.random.PRNGKey(1),
                    'negatives': jax.random.PRNGKey(2)}, g_s, g_t)


def test_dgmc_batch_pair_psi1_rejects_width_mismatch():
    """A psi_1 union with differing source/target feature widths must
    reject loudly, not silently benchmark the two-call path."""
    rng = np.random.RandomState(7)
    g_s = attach_blocks(random_graph(rng, 1, 60, 240, 24), rows=64,
                        block_edges=128, min_nodes=1)
    g_t = attach_blocks(random_graph(rng, 1, 80, 300, 16), rows=64,
                        block_edges=128, min_nodes=1)
    model = DGMC(RelCNN(24, 32, 2), RelCNN(8, 8, 2), num_steps=1, k=4,
                 batch_pair='psi_1')
    with pytest.raises(ValueError, match='widths differ'):
        model.init({'params': jax.random.PRNGKey(0),
                    'noise': jax.random.PRNGKey(1),
                    'negatives': jax.random.PRNGKey(2)}, g_s, g_t)
