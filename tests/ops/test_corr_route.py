"""Scatter-free candidate routing (ops/corr_route.py) vs segment-sum truth.

The routed formulation must agree with the gather/segment-sum form it
replaces — values AND gradients — including duplicate candidates (random
negatives can repeat a top-k column; GT injection overwrites the last
slot) and ragged range occupancy.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dgmc_tpu.ops.corr_route import (build_corr_route, sparse_gather,
                                     sparse_project)


def _random_case(seed, B=2, N_s=37, K=5, N_t=53, R=7, dupes=True):
    rng = np.random.RandomState(seed)
    S_idx = rng.randint(0, N_t, (B, N_s, K)).astype(np.int32)
    if dupes:  # force repeated targets inside single rows
        S_idx[:, ::3, -1] = S_idx[:, ::3, 0]
    S = rng.randn(B, N_s, K).astype(np.float32)
    r_s = rng.randn(B, N_s, R).astype(np.float32)
    feat = rng.randn(B, N_t, R).astype(np.float32)
    return jnp.asarray(S_idx), jnp.asarray(S), jnp.asarray(r_s), \
        jnp.asarray(feat)


def _project_ref(S, r_s, S_idx, N_t):
    B, N_s, K = S_idx.shape
    contrib = (S[..., None] * r_s[:, :, None, :]).reshape(
        B, N_s * K, r_s.shape[-1])

    def scat(c, idx):
        return jax.ops.segment_sum(c, idx, num_segments=N_t)

    return jax.vmap(scat)(contrib, S_idx.reshape(B, N_s * K))


@pytest.mark.parametrize('seed', [0, 1])
@pytest.mark.parametrize('rows,block_entries', [(8, 16), (16, 64)])
def test_project_matches_segment_sum(seed, rows, block_entries):
    S_idx, S, r_s, _ = _random_case(seed)
    N_t = 53
    route = build_corr_route(S_idx, N_t, rows=rows,
                             block_entries=block_entries)
    got = sparse_project(S, r_s, S_idx, route)
    want = _project_ref(S, r_s, S_idx, N_t)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_project_gradients_match():
    S_idx, S, r_s, _ = _random_case(3)
    N_t = 53
    route = build_corr_route(S_idx, N_t, rows=8, block_entries=32)

    def loss_routed(S, r_s):
        out = sparse_project(S, r_s, S_idx, route)
        return jnp.sum(jnp.sin(out))

    def loss_ref(S, r_s):
        return jnp.sum(jnp.sin(_project_ref(S, r_s, S_idx, N_t)))

    g1 = jax.grad(loss_routed, argnums=(0, 1))(S, r_s)
    g2 = jax.grad(loss_ref, argnums=(0, 1))(S, r_s)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)


def test_gather_values_and_gradients():
    S_idx, _, _, feat = _random_case(4)
    route = build_corr_route(S_idx, 53, rows=8, block_entries=32)

    got = sparse_gather(feat, S_idx, route)
    B, N_s, K = S_idx.shape
    want = jnp.take_along_axis(
        feat, S_idx.reshape(B, N_s * K)[..., None], axis=1).reshape(
            B, N_s, K, feat.shape[-1])
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    w = jnp.asarray(np.random.RandomState(9).randn(*want.shape)
                    .astype(np.float32))

    g1 = jax.grad(lambda f: jnp.sum(sparse_gather(f, S_idx, route) * w))(
        feat)
    g2 = jax.grad(lambda f: jnp.sum(jnp.take_along_axis(
        f, S_idx.reshape(B, N_s * K)[..., None], axis=1).reshape(
            B, N_s, K, -1) * w))(feat)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               atol=1e-5, rtol=1e-5)


def test_route_handles_hub_targets():
    """A single hub target absorbing most candidates forces multiple blocks
    in one range — the ragged static-blocking edge case."""
    rng = np.random.RandomState(7)
    B, N_s, K, N_t, R = 1, 64, 4, 40, 5
    S_idx = np.full((B, N_s, K), 3, np.int32)       # everything hits node 3
    S_idx[0, :10] = rng.randint(0, N_t, (10, K))
    S = rng.randn(B, N_s, K).astype(np.float32)
    r_s = rng.randn(B, N_s, R).astype(np.float32)
    route = build_corr_route(jnp.asarray(S_idx), N_t, rows=8,
                             block_entries=16)
    got = sparse_project(jnp.asarray(S), jnp.asarray(r_s),
                         jnp.asarray(S_idx), route)
    want = _project_ref(jnp.asarray(S), jnp.asarray(r_s),
                        jnp.asarray(S_idx), N_t)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_dgmc_route_forced_on_matches_off():
    """DGMC sparse forward/backward with route_sparse=True must match the
    segment-sum path at small scale (where the auto gate keeps it off)."""
    from dgmc_tpu.models import DGMC, RelCNN
    from dgmc_tpu.train import create_train_state, make_train_step
    from dgmc_tpu.utils.data import PairBatch
    from dgmc_tpu.ops.graph import GraphBatch

    rng = np.random.RandomState(0)
    N, E, C = 24, 60, 8

    def side(seed):
        r = np.random.RandomState(seed)
        return GraphBatch(
            x=r.randn(1, N, C).astype(np.float32),
            senders=r.randint(0, N, (1, E)).astype(np.int32),
            receivers=r.randint(0, N, (1, E)).astype(np.int32),
            node_mask=np.ones((1, N), bool),
            edge_mask=np.ones((1, E), bool), edge_attr=None)

    y = rng.permutation(N).astype(np.int32)[None]
    batch = PairBatch(s=side(1), t=side(2), y=y, y_mask=y >= 0)

    outs = []
    for forced in (True, False):
        model = DGMC(RelCNN(C, 12, num_layers=1),
                     RelCNN(8, 8, num_layers=1), num_steps=2, k=4,
                     route_sparse=forced)
        state = create_train_state(model, jax.random.key(0), batch,
                                   learning_rate=1e-2)
        step = make_train_step(model)
        state, out = step(state, batch, jax.random.key(1))
        state, out = step(state, batch, jax.random.key(2))
        outs.append((float(out['loss']), float(out['acc'])))
    np.testing.assert_allclose(outs[0][0], outs[1][0], atol=1e-4,
                               rtol=1e-4)
    assert outs[0][1] == outs[1][1]
