"""CON505 golden fixture: a shared list appended from a serving thread
with no cap, ring, or eviction anywhere in the class."""

import threading


class RequestLog:
    def __init__(self):
        self.history = []
        self.by_client = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._drain, daemon=True)
        self._thread.start()

    def _drain(self):
        while not self._stop.is_set():
            item = self._next()
            self.history.append(item)        # CON505: unbounded growth
            self.by_client[item] = item      # CON505: unbounded dict

    def _next(self):
        return object()

    def close(self):
        self._stop.set()
        self._thread.join()
