"""CON501 golden fixture: the PR-15 counter bug in miniature — a
counter read-modify-written from a daemon thread with no lock anywhere
in the class."""

import threading
import time


class Poller:
    def __init__(self):
        self.polls = 0
        self.last_status = None
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._stop.is_set():
            self.polls += 1              # CON501: unlocked += off-thread
            self.last_status = 'ok'      # plain rebind: exempt (atomic)
            time.sleep(0.01)

    def close(self):
        self._stop.set()
        self._thread.join()
