"""Clean controls for the CON tier: every hazardous shape done RIGHT —
locked counters, consistent lock order, tmp+rename artifact writes, a
flag-only signal handler, capped containers. Must lint silent under
every CON rule (and every SRC rule)."""

import collections
import json
import os
import signal
import threading

SHUTDOWN = threading.Event()


def _on_term(signum, frame):
    SHUTDOWN.set()                       # flag-only handler: safe


def install():
    signal.signal(signal.SIGTERM, _on_term)


def save_manifest_atomic(path, entries):
    scratch = f'{path}.tmp.{os.getpid()}'
    with open(scratch, 'w') as f:
        json.dump({'entries': entries}, f)
    os.replace(scratch, path)


class Server:
    def __init__(self):
        self._lock = threading.Lock()
        self._stats_lock = threading.Lock()
        self.served = 0
        self.recent = collections.deque(maxlen=256)
        self.by_client = {}
        self.capacity = 1024
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._stop.is_set():
            item = self._next()
            with self._stats_lock:       # guarded RMW: CON501-clean
                self.served += 1
            self.recent.append(item)     # maxlen ring: CON505-clean
            if len(self.by_client) < self.capacity:
                self.by_client[item] = item   # len-capped: CON505-clean
            else:
                self.by_client.pop(next(iter(self.by_client)))

    def _next(self):
        return object()

    def snapshot(self):
        with self._lock:                 # one order everywhere:
            with self._stats_lock:       # CON502-clean
                return self.served, len(self.recent)

    def reset(self):
        with self._lock:
            with self._stats_lock:       # same order again
                self.served = 0

    def close(self):
        self._stop.set()
        self._thread.join()
