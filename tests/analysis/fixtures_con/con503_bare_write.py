"""CON503 golden fixture: a consumed artifact written in place via
bare ``open(path, 'w')`` — no tmp suffix, no ``os.replace``."""

import json


def save_manifest(path, entries):
    with open(path, 'w') as f:               # CON503: in-place write
        json.dump({'entries': entries}, f)


def append_log(path, line):
    with open(path, 'a') as f:               # append: exempt
        f.write(line + '\n')
