"""Golden fixtures for the concurrency (CON) lint tier.

One module per rule, each detected by EXACTLY that rule (and by no
source-tier rule), plus ``clean_controls.py`` which exercises every
hazardous shape done right and must lint silent. The modules are data
for ``tests/analysis/test_con_rules.py`` — nothing imports them for
their behavior.
"""
