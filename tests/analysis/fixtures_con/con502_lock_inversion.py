"""CON502 golden fixture: two locks taken nested in opposite orders on
two call paths of one class — deadlock by construction."""

import threading


class Transfer:
    def __init__(self):
        self._accounts = threading.Lock()
        self._journal = threading.Lock()
        self.balance = {}
        self.entries = 0

    def debit(self, key, amount):
        with self._accounts:
            with self._journal:              # order: accounts -> journal
                self.balance[key] = self.balance.get(key, 0) - amount
                self.entries += 1

    def reconcile(self):
        with self._journal:
            with self._accounts:             # CON502: journal -> accounts
                return dict(self.balance), self.entries
