"""CON504 golden fixture: a signal handler that takes a lock and does
buffered IO — both unsafe with the main thread interrupted at an
arbitrary point."""

import signal
import threading

STATE_LOCK = threading.Lock()
STATE = {'requests': 0}


def _on_term(signum, frame):
    with STATE_LOCK:                         # CON504: lock in handler
        print('terminating:', STATE)         # CON504: buffered IO


def install():
    signal.signal(signal.SIGTERM, _on_term)
