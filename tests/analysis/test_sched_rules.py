"""SCH/MEM tier golden fixtures: each rule detected by exactly that
rule, plus clean controls, the real-specimen drive, and the committed
overlap/peak budgets of the streamed train step.

Like the SHD fixtures, these are hand-seeded partitioned-HLO programs:
the defect classes (an async pair that immediately blocks, a loop body
whose fetch chains every iteration, a 33 MiB residual slab riding the
loop carry) are read out of compiler output, wherever it came from.
"""

import jax
import pytest

from dgmc_tpu.analysis.hlo_liveness import module_peak
from dgmc_tpu.analysis.hlo_sched import module_schedules, schedule_summary
from dgmc_tpu.analysis.sched_rules import (SchedContext,
                                           analyze_schedule_hlo)


def _rules(findings):
    return sorted({f.rule for f in findings})


# --- SCH401: async pair serialized inside a while body ------------------

SERIAL_ASYNC_LOOP = (
    '%body (carry: (s32[], f32[64])) -> (s32[], f32[64]) {\n'
    '  %carry = (s32[], f32[64]{0}) parameter(0)\n'
    '  %s = f32[64]{0} get-tuple-element((s32[], f32[64]{0}) %carry),'
    ' index=1\n'
    '  %cps = f32[64]{0} collective-permute-start(f32[64]{0} %s),'
    ' channel_id=1, source_target_pairs={{0,1},{1,0}}\n'
    '  %cpd = f32[64]{0} collective-permute-done(f32[64]{0} %cps)\n'
    '  %m = f32[64]{0} multiply(f32[64]{0} %cpd, f32[64]{0} %cpd)\n'
    '  %i = s32[] get-tuple-element((s32[], f32[64]{0}) %carry),'
    ' index=0\n'
    '  ROOT %t = (s32[], f32[64]{0}) tuple(s32[] %i, f32[64]{0} %m)\n'
    '}\n'
    '\n'
    '%cond (c: (s32[], f32[64])) -> pred[] {\n'
    '  %c = (s32[], f32[64]{0}) parameter(0)\n'
    '  %i.1 = s32[] get-tuple-element((s32[], f32[64]{0}) %c), index=0\n'
    '  %lim = s32[] constant(8)\n'
    '  ROOT %lt = pred[] compare(s32[] %i.1, s32[] %lim), direction=LT\n'
    '}\n'
    '\n'
    'ENTRY %main (x: f32[64], i0: s32[]) -> f32[64] {\n'
    '  %x = f32[64]{0} parameter(0)\n'
    '  %i0 = s32[] parameter(1)\n'
    '  %init = (s32[], f32[64]{0}) tuple(s32[] %i0, f32[64]{0} %x)\n'
    '  %loop = (s32[], f32[64]{0}) while((s32[], f32[64]{0}) %init),'
    ' condition=%cond, body=%body\n'
    '  ROOT %out = f32[64]{0}'
    ' get-tuple-element((s32[], f32[64]{0}) %loop), index=1\n'
    '}\n'
)

# Control: per-tile compute between the start and its done — the
# transfer hides behind it, exactly what the streamed layout wants.
OVERLAPPED_ASYNC_LOOP = SERIAL_ASYNC_LOOP.replace(
    '  %cpd = f32[64]{0} collective-permute-done(f32[64]{0} %cps)\n'
    '  %m = f32[64]{0} multiply(f32[64]{0} %cpd, f32[64]{0} %cpd)\n',
    '  %w = f32[64]{0} multiply(f32[64]{0} %s, f32[64]{0} %s)\n'
    '  %cpd = f32[64]{0} collective-permute-done(f32[64]{0} %cps)\n'
    '  %m = f32[64]{0} add(f32[64]{0} %cpd, f32[64]{0} %w)\n')


def test_sch401_serialized_async_pair_in_loop():
    findings = analyze_schedule_hlo(SERIAL_ASYNC_LOOP,
                                    SchedContext(specimen='fix'))
    assert _rules(findings) == ['SCH401']
    (f,) = findings
    assert f.severity.name == 'ERROR'
    assert 'serialized' in f.message
    assert f.where.startswith('fix:')
    assert f.context.startswith('collective-permute-start')


def test_sch401_overlapped_pair_is_clean():
    assert analyze_schedule_hlo(OVERLAPPED_ASYNC_LOOP,
                                SchedContext(specimen='fix')) == []


# --- SCH402: modeled overlap under the recorded budget ------------------

# A dependence-chained program: every op needs the collective's result,
# so the model can place no compute inside its window (overlap 0.0).
CHAINED_COMM = (
    '%add (a: f32[], b: f32[]) -> f32[] {\n'
    '  %a = f32[] parameter(0)\n'
    '  %b = f32[] parameter(1)\n'
    '  ROOT %s = f32[] add(f32[] %a, f32[] %b)\n'
    '}\n'
    '\n'
    'ENTRY %main (g: f32[1024]) -> f32[1024] {\n'
    '  %g = f32[1024]{0} parameter(0)\n'
    '  %n = f32[1024]{0} negate(f32[1024]{0} %g)\n'
    '  %ar = f32[1024]{0} all-reduce(f32[1024]{0} %n), channel_id=1,'
    ' replica_groups={{0,1}}, to_apply=%add\n'
    '  ROOT %n2 = f32[1024]{0} negate(f32[1024]{0} %ar)\n'
    '}\n'
)

# Control: the collective and an equal-sized compute chain are
# dependency-independent — the model overlaps them fully.
SLACK_COMM = (
    '%add (a: f32[], b: f32[]) -> f32[] {\n'
    '  %a = f32[] parameter(0)\n'
    '  %b = f32[] parameter(1)\n'
    '  ROOT %s = f32[] add(f32[] %a, f32[] %b)\n'
    '}\n'
    '\n'
    'ENTRY %main (g: f32[1024], h: f32[1024]) -> f32[1024] {\n'
    '  %g = f32[1024]{0} parameter(0)\n'
    '  %h = f32[1024]{0} parameter(1)\n'
    '  %ar = f32[1024]{0} all-reduce(f32[1024]{0} %g), channel_id=1,'
    ' replica_groups={{0,1}}, to_apply=%add\n'
    '  %m = f32[1024]{0} multiply(f32[1024]{0} %h, f32[1024]{0} %h)\n'
    '  ROOT %o = f32[1024]{0} add(f32[1024]{0} %ar, f32[1024]{0} %m)\n'
    '}\n'
)


def test_sch402_overlap_under_budget():
    ctx = SchedContext(specimen='fix', overlap_budget=0.5)
    findings = analyze_schedule_hlo(CHAINED_COMM, ctx)
    assert _rules(findings) == ['SCH402']
    (f,) = findings
    assert f.severity.name == 'WARNING'
    assert '0.5' in f.message
    assert 'measured 0.0' in f.detail


def test_sch402_slack_meets_budget():
    ctx = SchedContext(specimen='fix', overlap_budget=0.5)
    assert analyze_schedule_hlo(SLACK_COMM, ctx) == []
    assert schedule_summary(SLACK_COMM)['overlap_fraction'] == 1.0


def test_sch402_needs_a_budget():
    assert analyze_schedule_hlo(CHAINED_COMM,
                                SchedContext(specimen='fix')) == []


# --- SCH403: per-iteration fetch serialized behind the carry ------------

def _fetch_loop(slice_elems):
    slice_ty = f'f32[{slice_elems}]'
    return (
        f'%body (carry: (s32[], f32[1048576], {slice_ty})) ->'
        f' (s32[], f32[1048576], {slice_ty}) {{\n'
        f'  %carry = (s32[], f32[1048576]{{0}}, {slice_ty}{{0}})'
        f' parameter(0)\n'
        f'  %i = s32[] get-tuple-element((s32[], f32[1048576]{{0}},'
        f' {slice_ty}{{0}}) %carry), index=0\n'
        f'  %tab = f32[1048576]{{0}} get-tuple-element((s32[],'
        f' f32[1048576]{{0}}, {slice_ty}{{0}}) %carry), index=1\n'
        f'  %acc = {slice_ty}{{0}} get-tuple-element((s32[],'
        f' f32[1048576]{{0}}, {slice_ty}{{0}}) %carry), index=2\n'
        f'  %ds = {slice_ty}{{0}} dynamic-slice(f32[1048576]{{0}} %tab,'
        f' s32[] %i), dynamic_slice_sizes={{{slice_elems}}}\n'
        f'  %m = {slice_ty}{{0}} multiply({slice_ty}{{0}} %ds,'
        f' {slice_ty}{{0}} %ds)\n'
        f'  %a2 = {slice_ty}{{0}} add({slice_ty}{{0}} %m,'
        f' {slice_ty}{{0}} %acc)\n'
        f'  %one = s32[] constant(1)\n'
        f'  %i2 = s32[] add(s32[] %i, s32[] %one)\n'
        f'  ROOT %t = (s32[], f32[1048576]{{0}}, {slice_ty}{{0}})'
        f' tuple(s32[] %i2, f32[1048576]{{0}} %tab,'
        f' {slice_ty}{{0}} %a2)\n'
        f'}}\n'
        f'\n'
        f'%cond (c: (s32[], f32[1048576], {slice_ty})) -> pred[] {{\n'
        f'  %c = (s32[], f32[1048576]{{0}}, {slice_ty}{{0}})'
        f' parameter(0)\n'
        f'  %i.1 = s32[] get-tuple-element((s32[], f32[1048576]{{0}},'
        f' {slice_ty}{{0}}) %c), index=0\n'
        f'  %lim = s32[] constant(4)\n'
        f'  ROOT %lt = pred[] compare(s32[] %i.1, s32[] %lim),'
        f' direction=LT\n'
        f'}}\n'
        f'\n'
        f'ENTRY %main (t0: f32[1048576], a0: {slice_ty}, i0: s32[]) ->'
        f' {slice_ty} {{\n'
        f'  %t0 = f32[1048576]{{0}} parameter(0)\n'
        f'  %a0 = {slice_ty}{{0}} parameter(1)\n'
        f'  %i0 = s32[] parameter(2)\n'
        f'  %init = (s32[], f32[1048576]{{0}}, {slice_ty}{{0}})'
        f' tuple(s32[] %i0, f32[1048576]{{0}} %t0,'
        f' {slice_ty}{{0}} %a0)\n'
        f'  %loop = (s32[], f32[1048576]{{0}}, {slice_ty}{{0}})'
        f' while((s32[], f32[1048576]{{0}}, {slice_ty}{{0}}) %init),'
        f' condition=%cond, body=%body\n'
        f'  ROOT %out = {slice_ty}{{0}} get-tuple-element((s32[],'
        f' f32[1048576]{{0}}, {slice_ty}{{0}}) %loop), index=2\n'
        f'}}\n'
    )


#: 262144 f32 = 1 MiB fetched per iteration off the carry.
BIG_FETCH_LOOP = _fetch_loop(262144)
#: 64 f32 = 256 B per iteration — not worth pipelining.
SMALL_FETCH_LOOP = _fetch_loop(64)


def test_sch403_big_serial_fetch_is_double_buffer_opportunity():
    findings = analyze_schedule_hlo(BIG_FETCH_LOOP,
                                    SchedContext(specimen='fix'))
    assert _rules(findings) == ['SCH403']
    (f,) = findings
    assert f.severity.name == 'INFO'
    assert 'double-buffer' in f.message
    assert 'dynamic-slice' in f.message
    assert 'ROADMAP item 4' in f.detail


def test_sch403_small_fetch_is_clean():
    assert analyze_schedule_hlo(SMALL_FETCH_LOOP,
                                SchedContext(specimen='fix')) == []


# --- MEM404: static peak over the device budget -------------------------

BIG_PEAK = (
    'ENTRY %main (p: f32[262144]) -> f32[262144] {\n'
    '  %p = f32[262144]{0} parameter(0)\n'
    '  %a = f32[262144]{0} negate(f32[262144]{0} %p), metadata={'
    'op_name="jit(f)/jit(main)/psi1/neg"}\n'
    '  %b = f32[262144]{0} negate(f32[262144]{0} %a), metadata={'
    'op_name="jit(f)/jit(main)/consensus_iter/neg"}\n'
    '  ROOT %c = f32[262144]{0} add(f32[262144]{0} %a,'
    ' f32[262144]{0} %b)\n'
    '}\n'
)


def test_mem404_peak_over_budget():
    # Peak: p (freed after %a... p's last use is %a) — at %b: a+b+p?
    # p frees after %a, so peak point holds p+a (at %a) then a+b(+c).
    # 3 buffers of 1 MiB overlap at the peak; a 2 MiB budget trips.
    ctx = SchedContext(specimen='fix', peak_bytes_budget=2 << 20)
    findings = analyze_schedule_hlo(BIG_PEAK, ctx)
    assert _rules(findings) == ['MEM404']
    (f,) = findings
    assert f.severity.name == 'ERROR'
    assert 'psi1' in f.detail or 'consensus_iter' in f.detail


def test_mem404_within_budget_is_clean():
    ctx = SchedContext(specimen='fix', peak_bytes_budget=8 << 20)
    assert analyze_schedule_hlo(BIG_PEAK, ctx) == []


def test_mem404_needs_a_budget():
    assert analyze_schedule_hlo(BIG_PEAK,
                                SchedContext(specimen='fix')) == []


# --- MEM405: loop-carried full-axis residual ----------------------------

# The PR 9 shape: one pred slab PER CHUNK stacked across the whole
# streamed axis (leading dim = trip count 16384/128 = 128), riding the
# while carry as a backward residual — 32 MiB for a loop whose real
# state is the f32[2048,64] accumulator (512 KiB, chunk-scaled).
RESIDUAL_LOOP = (
    '%body (carry: (s32[], pred[128,2048,128], f32[2048,64])) ->'
    ' (s32[], pred[128,2048,128], f32[2048,64]) {\n'
    '  %carry = (s32[], pred[128,2048,128]{2,1,0}, f32[2048,64]{1,0})'
    ' parameter(0)\n'
    '  %i = s32[] get-tuple-element((s32[], pred[128,2048,128]{2,1,0},'
    ' f32[2048,64]{1,0}) %carry), index=0\n'
    '  %mask = pred[128,2048,128]{2,1,0} get-tuple-element((s32[],'
    ' pred[128,2048,128]{2,1,0}, f32[2048,64]{1,0}) %carry), index=1\n'
    '  %acc = f32[2048,64]{1,0} get-tuple-element((s32[],'
    ' pred[128,2048,128]{2,1,0}, f32[2048,64]{1,0}) %carry), index=2\n'
    '  %one = s32[] constant(1)\n'
    '  %i2 = s32[] add(s32[] %i, s32[] %one)\n'
    '  ROOT %t = (s32[], pred[128,2048,128]{2,1,0},'
    ' f32[2048,64]{1,0}) tuple(s32[] %i2,'
    ' pred[128,2048,128]{2,1,0} %mask, f32[2048,64]{1,0} %acc)\n'
    '}\n'
    '\n'
    '%cond (c: (s32[], pred[128,2048,128], f32[2048,64])) -> pred[] {\n'
    '  %c = (s32[], pred[128,2048,128]{2,1,0}, f32[2048,64]{1,0})'
    ' parameter(0)\n'
    '  %i.1 = s32[] get-tuple-element((s32[],'
    ' pred[128,2048,128]{2,1,0}, f32[2048,64]{1,0}) %c), index=0\n'
    '  %lim = s32[] constant(128)\n'
    '  ROOT %lt = pred[] compare(s32[] %i.1, s32[] %lim),'
    ' direction=LT\n'
    '}\n'
    '\n'
    'ENTRY %main (m0: pred[128,2048,128], a0: f32[2048,64],'
    ' i0: s32[]) -> f32[2048,64] {\n'
    '  %m0 = pred[128,2048,128]{2,1,0} parameter(0)\n'
    '  %a0 = f32[2048,64]{1,0} parameter(1)\n'
    '  %i0 = s32[] parameter(2)\n'
    '  %init = (s32[], pred[128,2048,128]{2,1,0}, f32[2048,64]{1,0})'
    ' tuple(s32[] %i0, pred[128,2048,128]{2,1,0} %m0,'
    ' f32[2048,64]{1,0} %a0)\n'
    '  %loop = (s32[], pred[128,2048,128]{2,1,0}, f32[2048,64]{1,0})'
    ' while((s32[], pred[128,2048,128]{2,1,0}, f32[2048,64]{1,0})'
    ' %init), condition=%cond, body=%body\n'
    '  ROOT %out = f32[2048,64]{1,0} get-tuple-element((s32[],'
    ' pred[128,2048,128]{2,1,0}, f32[2048,64]{1,0}) %loop), index=2\n'
    '}\n'
)

# Control: the same loop carrying only chunk-scaled state.
CHUNK_LOOP = RESIDUAL_LOOP.replace('pred[128,2048,128]', 'pred[2048,128]')


def test_mem405_full_axis_residual():
    ctx = SchedContext(specimen='fix', stream_full=16384,
                       stream_chunk=128)
    findings = analyze_schedule_hlo(RESIDUAL_LOOP, ctx)
    assert _rules(findings) == ['MEM405']
    (f,) = findings
    assert f.severity.name == 'ERROR'
    assert 'pred[128,2048,128]' in f.message
    assert 'trip count' in f.detail
    assert f.context == 'while carry pred[128,2048,128]'


def test_mem405_chunk_scaled_carry_is_clean():
    ctx = SchedContext(specimen='fix', stream_full=16384,
                       stream_chunk=128)
    assert analyze_schedule_hlo(CHUNK_LOOP, ctx) == []


def test_mem405_needs_stream_decl():
    assert analyze_schedule_hlo(RESIDUAL_LOOP,
                                SchedContext(specimen='fix')) == []


def test_mem405_unrelated_wide_dim_is_not_the_streamed_axis():
    """A legitimate carried accumulator with a big FEATURE dim (256)
    must not read as 'carries the corpus axis' just because 256 >= the
    streamed axis length — only a dim EQUAL to stream_full (or the
    per-chunk stacking signature) is the class."""
    legit = RESIDUAL_LOOP.replace('pred[128,2048,128]', 'f32[8,256]')
    ctx = SchedContext(specimen='fix', stream_full=16,
                       stream_chunk=8, residual_min_bytes=4096)
    assert analyze_schedule_hlo(legit, ctx) == []   # 8 KiB, clears floor


# Pipelined (double-buffered) loop: the -start issues at the END of the
# body and threads OUT through the carry; its -done is consumed across
# the back-edge. SCH401 must NOT flag the pattern its own remediation
# recommends.
PIPELINED_ASYNC_LOOP = SERIAL_ASYNC_LOOP.replace(
    '  %cps = f32[64]{0} collective-permute-start(f32[64]{0} %s),'
    ' channel_id=1, source_target_pairs={{0,1},{1,0}}\n'
    '  %cpd = f32[64]{0} collective-permute-done(f32[64]{0} %cps)\n'
    '  %m = f32[64]{0} multiply(f32[64]{0} %cpd, f32[64]{0} %cpd)\n',
    '  %m = f32[64]{0} multiply(f32[64]{0} %s, f32[64]{0} %s)\n'
    '  %cps = f32[64]{0} collective-permute-start(f32[64]{0} %m),'
    ' channel_id=1, source_target_pairs={{0,1},{1,0}}\n').replace(
    'tuple(s32[] %i, f32[64]{0} %m)', 'tuple(s32[] %i, f32[64]{0} %cps)')


def test_sch401_skips_cross_iteration_pipelined_start():
    assert analyze_schedule_hlo(PIPELINED_ASYNC_LOOP,
                                SchedContext(specimen='fix')) == []


# --- the schedule model itself ------------------------------------------


def test_schedule_model_async_interval_overlap():
    """The list schedule widens an async pair into an interval and
    measures the independent compute inside it."""
    scheds = module_schedules(OVERLAPPED_ASYNC_LOOP)
    (coll,) = scheds['body'].collectives
    assert coll.program_gap_cost and coll.program_gap_cost > 0
    assert coll.overlap_fraction == 1.0
    (serial,) = module_schedules(SERIAL_ASYNC_LOOP)['body'].collectives
    assert serial.program_gap_cost == 0


def test_schedule_model_critical_path_share():
    """A pure chain has share 1.0; the slack program sits below it."""
    chained = module_schedules(CHAINED_COMM)['main']
    assert chained.critical_path_share == 1.0
    slack = module_schedules(SLACK_COMM)['main']
    assert slack.critical_path_share < 1.0


# A ring-style loop: the boundary permute lives in a while body whose
# trip count XLA proved constant (backend_config) — the payload
# weighting must count it once per trip, next to a one-shot entry
# all-reduce of the same static size.
TRIP_AMPLIFIED_LOOP = (
    '%add (a: f32[], b: f32[]) -> f32[] {\n'
    '  %a = f32[] parameter(0)\n'
    '  %b = f32[] parameter(1)\n'
    '  ROOT %s = f32[] add(f32[] %a, f32[] %b)\n'
    '}\n'
    '\n'
    '%body (carry: (s32[], f32[64])) -> (s32[], f32[64]) {\n'
    '  %carry = (s32[], f32[64]{0}) parameter(0)\n'
    '  %s = f32[64]{0} get-tuple-element((s32[], f32[64]{0}) %carry),'
    ' index=1\n'
    '  %cp = f32[64]{0} collective-permute(f32[64]{0} %s),'
    ' channel_id=1, source_target_pairs={{0,1},{1,2},{2,3},{3,0}}\n'
    '  %m = f32[64]{0} multiply(f32[64]{0} %s, f32[64]{0} %s)\n'
    '  %m2 = f32[64]{0} multiply(f32[64]{0} %m, f32[64]{0} %cp)\n'
    '  %i = s32[] get-tuple-element((s32[], f32[64]{0}) %carry),'
    ' index=0\n'
    '  ROOT %t = (s32[], f32[64]{0}) tuple(s32[] %i, f32[64]{0} %m2)\n'
    '}\n'
    '\n'
    '%cond (c: (s32[], f32[64])) -> pred[] {\n'
    '  %c = (s32[], f32[64]{0}) parameter(0)\n'
    '  %i.1 = s32[] get-tuple-element((s32[], f32[64]{0}) %c), index=0\n'
    '  %lim = s32[] constant(8)\n'
    '  ROOT %lt = pred[] compare(s32[] %i.1, s32[] %lim), direction=LT\n'
    '}\n'
    '\n'
    'ENTRY %main (x: f32[64], i0: s32[]) -> f32[64] {\n'
    '  %x = f32[64]{0} parameter(0)\n'
    '  %i0 = s32[] parameter(1)\n'
    '  %ar = f32[64]{0} all-reduce(f32[64]{0} %x), channel_id=2,'
    ' replica_groups={{0,1,2,3}}, to_apply=%add\n'
    '  %init = (s32[], f32[64]{0}) tuple(s32[] %i0, f32[64]{0} %ar)\n'
    '  %loop = (s32[], f32[64]{0}) while((s32[], f32[64]{0}) %init),'
    ' condition=%cond, body=%body,'
    ' backend_config={"known_trip_count":{"n":"8"}}\n'
    '  ROOT %out = f32[64]{0}'
    ' get-tuple-element((s32[], f32[64]{0}) %loop), index=1\n'
    '}\n'
)


def test_trip_count_amplifies_loop_collective_weight():
    """A collective inside a known-trip-count while body weighs its
    bytes once PER TRIP in the program summary — per-execution bytes
    moved, not static op count — so an overlapped in-loop boundary
    permute carries its real weight against one-shot reductions."""
    from dgmc_tpu.analysis.hlo_sched import computation_trip_factors
    factors = computation_trip_factors(TRIP_AMPLIFIED_LOOP)
    assert factors['main'] == 1
    assert factors['body'] == 8
    summary = schedule_summary(TRIP_AMPLIFIED_LOOP)
    # 256 B permute x 8 trips + 256 B one-shot all-reduce.
    assert summary['collective_bytes'] == 256 * 8 + 256
    assert summary['collective_count'] == 2
    assert summary['loop_collectives'] == 1
    # The in-loop permute is independent of the body's compute chain
    # (issued off the carry) -> overlapped; the entry all-reduce feeds
    # everything -> serialized. The amplified weighting must therefore
    # land near 8/9, not the unamplified 1/2.
    assert summary['overlap_fraction'] > 0.8


def test_unknown_trip_count_stays_conservative():
    """No known_trip_count -> multiplier 1 (the old reading)."""
    from dgmc_tpu.analysis.hlo_sched import computation_trip_factors
    stripped = TRIP_AMPLIFIED_LOOP.replace(
        ', backend_config={"known_trip_count":{"n":"8"}}', '')
    factors = computation_trip_factors(stripped)
    assert factors['body'] == 1
    assert schedule_summary(stripped)['collective_bytes'] == 512


def test_liveness_region_peak_stacks_on_caller():
    """The while body's working set rides on the caller's live set: the
    module peak exceeds the flat entry peak."""
    lv = module_peak(BIG_FETCH_LOOP)
    assert lv.region_name == 'body'
    assert lv.region_bytes > 0
    # Carry (4 MiB table + 1 MiB acc) + body interior (fetch + multiply
    # + next acc) all live across the loop.
    assert lv.peak_bytes > 6 << 20


# --- real specimens through the tier driver -----------------------------


@pytest.mark.skipif(len(jax.devices()) < 4, reason='needs 4 devices')
def test_sched_tier_runs_clean_on_registered_specimens():
    """The registered sched-tier specimens produce ONLY SCH/MEM findings
    (today: none — the committed budgets hold; a future finding lands in
    the baseline as a reviewed entry, never as drift in another
    tier)."""
    from dgmc_tpu.analysis.registry import SpecimenCache
    from dgmc_tpu.analysis.sched_rules import run_sched_tier
    cache = SpecimenCache()
    findings = run_sched_tier(cache=cache)
    assert all(f.rule.startswith(('SCH', 'MEM')) for f in findings)
    assert findings == [], [f.to_json() for f in findings]


@pytest.mark.skipif(len(jax.devices()) < 4, reason='needs 4 devices')
def test_streamed_specimen_overlap_and_peak_budgets_pinned():
    """The streamed train step's measured overlap fraction stays at or
    above its committed budget (a sharding edit that serializes the
    chunk loop or drops the ring fails here AND as SCH402 in CI), and
    its static peak stays under the committed byte budget (the
    fixture-scale face of the SCALE_r07/r08 per-device memory claims).

    The budget is the RAISED post-pipeline pin: the pre-rewrite loop
    committed 0.12 against a measured 0.1353; the double-buffered +
    ring-rotated rewrite commits 0.24 (2x) against a measured ~0.31 —
    the old pin is retired, not loosened."""
    from dgmc_tpu.analysis.registry import SpecimenCache, default_specimens
    (spec,) = [s for s in default_specimens()
               if s.name == 'parallel.streamed_train_step']
    art = SpecimenCache().artifacts(spec)
    built = art.built()
    text = art.compiled().as_text()
    summary = schedule_summary(text)
    assert built['overlap_budget'] == 0.24
    assert summary['overlap_fraction'] >= built['overlap_budget'], (
        'streamed chunk loop serialized: modeled overlap '
        f'{summary["overlap_fraction"]} fell under the committed '
        f'{built["overlap_budget"]} budget')
    # The win comes from the pipelined loop itself: the ring boundary
    # permute must live INSIDE a while body (loop-amplified weight) —
    # a rewrite that hoists or drops it reads as serialization here
    # before it ever reaches silicon.
    assert summary['loop_collectives'] >= 1, summary
    peak = module_peak(text).peak_bytes
    assert built['peak_bytes_budget'] == 40 << 10
    assert 0 < peak <= built['peak_bytes_budget'], (
        f'static peak {peak} B over the committed budget')
    # MEM405's floor is scaled to the fixture (largest legitimate
    # carries — the rotating target shard and the prefetched chunk
    # slot — stay under 2 KiB), not the GiB-class default that would
    # make it inert. SCH403's floor is armed LOW (128 B): the sched
    # tier staying clean on this specimen IS the pin that the
    # rewritten loops keep every per-iteration fetch off the
    # carry-chained critical path.
    assert built['residual_min_bytes'] == 4 << 10
    assert built['double_buffer_min_bytes'] == 128
