"""Lint runtime: one build/trace/lower/compile per specimen per run.

Before the :class:`SpecimenCache`, the trace tier and every other
consumer of a specimen each traced and compiled their own copy of the
program (the donation rule compiled one, ``obs.cost`` lowered another).
These tests pin the dedup at the ``jax.stages`` boundary: running the
trace tier AND the sharded tier over the same donating mesh specimen
costs exactly one ``Traced.lower`` and one ``Lowered.compile``.
"""

import jax
import jax.stages
import pytest

from dgmc_tpu.analysis.registry import (SpecimenCache, default_specimens,
                                        run_trace_tier)
from dgmc_tpu.analysis.shd_rules import run_sharded_tier


def _specimen(name):
    (spec,) = [s for s in default_specimens() if s.name == name]
    return spec


@pytest.fixture
def stage_counters(monkeypatch):
    calls = {'lower': 0, 'compile': 0}
    orig_lower = jax.stages.Traced.lower
    orig_compile = jax.stages.Lowered.compile

    def lower(self, *a, **k):
        calls['lower'] += 1
        return orig_lower(self, *a, **k)

    def compile(self, *a, **k):  # noqa: A001 - jax's own method name
        calls['compile'] += 1
        return orig_compile(self, *a, **k)

    monkeypatch.setattr(jax.stages.Traced, 'lower', lower)
    monkeypatch.setattr(jax.stages.Lowered, 'compile', compile)
    return calls


@pytest.mark.skipif(len(jax.devices()) < 2, reason='needs 2 devices')
def test_trace_and_sharded_tiers_share_one_lowering(stage_counters):
    """The donating GSPMD train-step specimen crosses BOTH tiers (jaxpr
    + donation rules, then the SHD communication rules) on a single
    lowering and a single compile."""
    spec = _specimen('parallel.sharded_train_step')
    cache = SpecimenCache()
    run_trace_tier([spec], cache=cache)
    run_sharded_tier([spec], cache=cache)
    assert stage_counters == {'lower': 1, 'compile': 1}
    assert cache.stats()[spec.name] == {
        'builds': 1, 'traces': 1, 'lowerings': 1, 'compiles': 1}


def test_non_donating_specimen_never_compiles(stage_counters):
    """A single-device, non-donating specimen needs only its jaxpr —
    the trace tier must not pay a lowering or a compile for it."""
    spec = _specimen('ops.masked_softmax')
    cache = SpecimenCache()
    run_trace_tier([spec], cache=cache)
    assert stage_counters == {'lower': 0, 'compile': 0}
    assert cache.stats()[spec.name] == {
        'builds': 1, 'traces': 1, 'lowerings': 0, 'compiles': 0}


def test_artifacts_are_lazy_and_idempotent():
    """Repeated artifact pulls return the same objects without
    re-running any stage."""
    spec = _specimen('ops.masked_softmax')
    cache = SpecimenCache()
    art = cache.artifacts(spec)
    assert art is cache.artifacts(spec)
    j1 = art.closed_jaxpr()
    j2 = art.closed_jaxpr()
    assert j1 is j2
    assert art.stats['traces'] == 1
