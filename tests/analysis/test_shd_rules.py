"""SHD tier golden fixtures: each rule detected by exactly that rule,
plus clean controls and the real-specimen drive.

The fixtures are hand-seeded partitioned-HLO programs — the defect
classes (a branch-divergent collective, an f32->bf16 downcast before a
reduce) cannot be coaxed out of healthy jax code on purpose, which is
the point of a static analyzer: it reads what the compiler produced,
wherever it came from.
"""

import jax
import pytest

from dgmc_tpu.analysis.shd_rules import ShardedContext, analyze_sharded_hlo


def _rules(findings):
    return sorted({f.rule for f in findings})


# --- SHD301: deliberately-seeded branch-divergent collective ------------

DIVERGENT_COND = (
    '%add (a: f32[], b: f32[]) -> f32[] {\n'
    '  %a = f32[] parameter(0)\n'
    '  %b = f32[] parameter(1)\n'
    '  ROOT %s = f32[] add(f32[] %a, f32[] %b)\n'
    '}\n'
    '\n'
    '%branch_comm (p0: f32[4]) -> f32[4] {\n'
    '  %p0 = f32[4]{0} parameter(0)\n'
    '  ROOT %ar = f32[4]{0} all-reduce(f32[4]{0} %p0), channel_id=2,'
    ' replica_groups={{0,1},{2,3}}, to_apply=%add\n'
    '}\n'
    '\n'
    '%branch_silent (p1: f32[4]) -> f32[4] {\n'
    '  ROOT %p1 = f32[4]{0} parameter(0)\n'
    '}\n'
    '\n'
    'ENTRY %main (pred.1: s32[], x: f32[4]) -> f32[4] {\n'
    '  %pred.1 = s32[] parameter(0)\n'
    '  %x = f32[4]{0} parameter(1)\n'
    '  ROOT %c = f32[4]{0} conditional(s32[] %pred.1, f32[4]{0} %x,'
    ' f32[4]{0} %x),'
    ' branch_computations={%branch_comm, %branch_silent}\n'
    '}\n'
)

CONVERGENT_COND = DIVERGENT_COND.replace(
    'ROOT %p1 = f32[4]{0} parameter(0)',
    '%p1 = f32[4]{0} parameter(0)\n'
    '  ROOT %ar2 = f32[4]{0} all-reduce(f32[4]{0} %p1), channel_id=3, '
    'replica_groups={{0,1},{2,3}}, to_apply=%add')


def test_shd301_branch_divergent_collective():
    findings = analyze_sharded_hlo(DIVERGENT_COND,
                                   ShardedContext(specimen='fix'))
    assert _rules(findings) == ['SHD301']
    (f,) = findings
    assert f.severity.name == 'ERROR'
    assert '[all-reduce] vs []' in f.message
    assert f.where.startswith('fix:')


def test_shd301_matching_branches_are_clean():
    assert analyze_sharded_hlo(CONVERGENT_COND,
                               ShardedContext(specimen='fix')) == []


# --- SHD302: correspondence-shaped all-gather ---------------------------

CORR_GATHER = (
    'ENTRY %main (s_shard: f32[2,4,10]) -> f32[2,8,10] {\n'
    '  %s_shard = f32[2,4,10]{2,1,0} parameter(0)\n'
    '  ROOT %ag = f32[2,8,10]{2,1,0}'
    ' all-gather(f32[2,4,10]{2,1,0} %s_shard), channel_id=5,'
    ' replica_groups={{0,1}}, dimensions={1}, metadata={'
    'op_name="jit(fwd)/jit(main)/initial_corr/sharding_constraint"'
    ' source_file="/x/dgmc_tpu/models/dgmc.py" source_line=437}\n'
    '}\n'
)

PARAM_GATHER = (
    'ENTRY %main (w: f32[128]) -> f32[256] {\n'
    '  %w = f32[128]{0} parameter(0)\n'
    '  ROOT %ag = f32[256]{0} all-gather(f32[128]{0} %w),'
    ' channel_id=5, replica_groups={{0,1}}, dimensions={0}\n'
    '}\n'
)


def test_shd302_corr_shaped_all_gather():
    ctx = ShardedContext(specimen='fix', corr_bytes=2 * 8 * 10 * 4)
    findings = analyze_sharded_hlo(CORR_GATHER, ctx)
    assert _rules(findings) == ['SHD302']
    (f,) = findings
    assert f.severity.name == 'ERROR'
    assert 'f32[2,8,10]' in f.message
    assert f.where == 'fix:dgmc_tpu/models/dgmc.py:437'


def test_shd302_param_gather_is_clean():
    """A rank-1 parameter gather bigger than corr_bytes must NOT fire:
    the rule targets correspondence-SHAPED results, not any big
    gather."""
    ctx = ShardedContext(specimen='fix', corr_bytes=64)
    assert analyze_sharded_hlo(PARAM_GATHER, ctx) == []


def test_shd302_needs_declared_corr_shape():
    assert analyze_sharded_hlo(CORR_GATHER,
                               ShardedContext(specimen='fix')) == []


# --- SHD303: resharding churn in the loop body --------------------------

RESHARD_CHURN = (
    '%body (carry: (s32[], f32[4,8])) -> (s32[], f32[4,8]) {\n'
    '  %carry = (s32[], f32[4,8]{1,0}) parameter(0)\n'
    '  %s = f32[4,8]{1,0}'
    ' get-tuple-element((s32[], f32[4,8]{1,0}) %carry), index=1\n'
    '  %cp1 = f32[4,8]{1,0} collective-permute(f32[4,8]{1,0} %s),'
    ' channel_id=1, source_target_pairs={{0,1},{1,0}}\n'
    '  %neg = f32[4,8]{1,0} negate(f32[4,8]{1,0} %cp1)\n'
    '  %cp2 = f32[4,8]{1,0} collective-permute(f32[4,8]{1,0} %neg),'
    ' channel_id=2, source_target_pairs={{1,0},{0,1}}\n'
    '  %i = s32[] get-tuple-element((s32[], f32[4,8]{1,0}) %carry),'
    ' index=0\n'
    '  ROOT %t = (s32[], f32[4,8]{1,0}) tuple(s32[] %i,'
    ' f32[4,8]{1,0} %cp2)\n'
    '}\n'
    '\n'
    '%cond (c: (s32[], f32[4,8])) -> pred[] {\n'
    '  %c = (s32[], f32[4,8]{1,0}) parameter(0)\n'
    '  %i.1 = s32[] get-tuple-element((s32[], f32[4,8]{1,0}) %c),'
    ' index=0\n'
    '  %lim = s32[] constant(10)\n'
    '  ROOT %lt = pred[] compare(s32[] %i.1, s32[] %lim),'
    ' direction=LT\n'
    '}\n'
    '\n'
    'ENTRY %main (x: f32[4,8], i0: s32[]) -> f32[4,8] {\n'
    '  %x = f32[4,8]{1,0} parameter(0)\n'
    '  %i0 = s32[] parameter(1)\n'
    '  %init = (s32[], f32[4,8]{1,0}) tuple(s32[] %i0,'
    ' f32[4,8]{1,0} %x)\n'
    '  %loop = (s32[], f32[4,8]{1,0})'
    ' while((s32[], f32[4,8]{1,0}) %init), condition=%cond,'
    ' body=%body, metadata={'
    'op_name="jit(f)/jit(main)/consensus_iter/while"'
    ' source_file="/x/dgmc_tpu/models/dgmc.py" source_line=451}\n'
    '  ROOT %out = f32[4,8]{1,0}'
    ' get-tuple-element((s32[], f32[4,8]{1,0}) %loop), index=1\n'
    '}\n'
)


def test_shd303_reshard_churn_in_loop_body():
    findings = analyze_sharded_hlo(RESHARD_CHURN,
                                   ShardedContext(specimen='fix'))
    assert _rules(findings) == ['SHD303']
    (f,) = findings
    assert f.severity.name == 'WARNING'
    assert 'loop body' in f.message
    assert f.where == 'fix:dgmc_tpu/models/dgmc.py:451'


def test_shd303_single_permute_is_clean():
    one = RESHARD_CHURN.replace(
        '%cp2 = f32[4,8]{1,0} collective-permute(f32[4,8]{1,0} %neg), '
        'channel_id=2, source_target_pairs={{1,0},{0,1}}',
        '%cp2 = f32[4,8]{1,0} negate(f32[4,8]{1,0} %neg)')
    assert analyze_sharded_hlo(one, ShardedContext(specimen='fix')) == []


def _independent_permutes(fixture):
    """Decouple the fixture's two permutes: cp2 reads the carried state
    directly instead of cp1's result — two INDEPENDENT per-iteration
    transfers (the ring pattern: target shard + its mask), no
    composition."""
    return fixture.replace(
        '%cp2 = f32[4,8]{1,0} collective-permute(f32[4,8]{1,0} %neg)',
        '%cp2 = f32[4,8]{1,0} collective-permute(f32[4,8]{1,0} %s)')


def test_shd303_ring_rotation_permutes_are_exempt():
    """The pipelined streamed-S ring re-issues INDEPENDENT boundary
    permutes (the rotating target shard and its mask) every iteration —
    no permute feeds another, so the layout never bounces and SHD303
    must stay silent, at any ring size."""
    ring = _independent_permutes(RESHARD_CHURN).replace(
        'source_target_pairs={{0,1},{1,0}}',
        'source_target_pairs={{0,1},{1,2},{2,3},{3,0}}').replace(
        'source_target_pairs={{1,0},{0,1}}',
        'source_target_pairs={{0,1},{1,2},{2,3},{3,0}}')
    assert analyze_sharded_hlo(ring, ShardedContext(specimen='fix')) == []


def test_shd303_two_device_ring_is_exempt_too():
    """A 2-shard ring's rotation {(0,1),(1,0)} is its own inverse —
    indistinguishable from a swap by source_target_pairs alone — so
    the exemption must key on COMPOSITION, not on the permutation:
    independent self-inverse permutes are the 2-device ring, clean."""
    ring2 = _independent_permutes(RESHARD_CHURN)
    assert analyze_sharded_hlo(ring2,
                               ShardedContext(specimen='fix')) == []


def test_shd303_composed_rotations_still_fire():
    """Forward-rotation source_target_pairs do NOT launder a bounce: a
    permute FED BY another permute (through the body's dataflow) is the
    round trip the rule exists for, whatever the mapping spells."""
    bounced = RESHARD_CHURN.replace(
        'source_target_pairs={{0,1},{1,0}}',
        'source_target_pairs={{0,1},{1,2},{2,3},{3,0}}').replace(
        'source_target_pairs={{1,0},{0,1}}',
        'source_target_pairs={{1,0},{2,1},{3,2},{0,3}}')
    findings = analyze_sharded_hlo(bounced, ShardedContext(specimen='fix'))
    assert _rules(findings) == ['SHD303']


# --- SHD304: communication budget ---------------------------------------

BIG_COMM = (
    '%add (a: f32[], b: f32[]) -> f32[] {\n'
    '  %a = f32[] parameter(0)\n'
    '  %b = f32[] parameter(1)\n'
    '  ROOT %s = f32[] add(f32[] %a, f32[] %b)\n'
    '}\n'
    '\n'
    'ENTRY %main (g: f32[1024,64]) -> f32[1024,64] {\n'
    '  %g = f32[1024,64]{1,0} parameter(0)\n'
    '  ROOT %ar = f32[1024,64]{1,0}'
    ' all-reduce(f32[1024,64]{1,0} %g), channel_id=1,'
    ' replica_groups={{0,1}}, to_apply=%add\n'
    '}\n'
)


def test_shd304_comm_budget_exceeded():
    ctx = ShardedContext(specimen='fix', comm_budget_bytes=1024)
    findings = analyze_sharded_hlo(BIG_COMM, ctx)
    assert _rules(findings) == ['SHD304']
    (f,) = findings
    assert f.severity.name == 'WARNING'
    assert f.where == 'fix:comm-budget'
    assert '<= 256 KiB' in f.message        # 1024*64*4 = 256 KiB exactly
    assert 'all-reduce: 262144 B' in f.detail


def test_shd304_within_budget_is_clean():
    ctx = ShardedContext(specimen='fix', comm_budget_bytes=1 << 20)
    assert analyze_sharded_hlo(BIG_COMM, ctx) == []


def test_shd304_needs_a_budget():
    assert analyze_sharded_hlo(BIG_COMM,
                               ShardedContext(specimen='fix')) == []


# --- SHD305: f32->bf16 downcast before a reduction ----------------------

DOWNCAST_REDUCE = (
    '%sum (a: bf16[], b: bf16[]) -> bf16[] {\n'
    '  %a = bf16[] parameter(0)\n'
    '  %b = bf16[] parameter(1)\n'
    '  ROOT %s = bf16[] add(bf16[] %a, bf16[] %b)\n'
    '}\n'
    '\n'
    'ENTRY %main (x: f32[128,128]) -> bf16[128] {\n'
    '  %x = f32[128,128]{1,0} parameter(0)\n'
    '  %cast = bf16[128,128]{1,0} convert(f32[128,128]{1,0} %x),'
    ' metadata={op_name="jit(f)/jit(main)/loss/convert"'
    ' source_file="/x/dgmc_tpu/train/steps.py" source_line=88}\n'
    '  %zero = bf16[] constant(0)\n'
    '  ROOT %r = bf16[128]{0} reduce(bf16[128,128]{1,0} %cast,'
    ' bf16[] %zero), dimensions={1}, to_apply=%sum, metadata={'
    'op_name="jit(f)/jit(main)/loss/reduce_sum"'
    ' source_file="/x/dgmc_tpu/train/steps.py" source_line=90}\n'
    '}\n'
)

F32_ACCUM_REDUCE = (
    '%sum (a: f32[], b: f32[]) -> f32[] {\n'
    '  %a = f32[] parameter(0)\n'
    '  %b = f32[] parameter(1)\n'
    '  ROOT %s = f32[] add(f32[] %a, f32[] %b)\n'
    '}\n'
    '\n'
    'ENTRY %main (x: bf16[128,128]) -> f32[128] {\n'
    '  %x = bf16[128,128]{1,0} parameter(0)\n'
    '  %cast = f32[128,128]{1,0} convert(bf16[128,128]{1,0} %x)\n'
    '  %zero = f32[] constant(0)\n'
    '  ROOT %r = f32[128]{0} reduce(f32[128,128]{1,0} %cast,'
    ' f32[] %zero), dimensions={1}, to_apply=%sum\n'
    '}\n'
)

BF16_DOT = (
    'ENTRY %main (a: bf16[8,512], b: bf16[512,8]) -> bf16[8,8] {\n'
    '  %a = bf16[8,512]{1,0} parameter(0)\n'
    '  %b = bf16[512,8]{1,0} parameter(1)\n'
    '  ROOT %d = bf16[8,8]{1,0} dot(bf16[8,512]{1,0} %a,'
    ' bf16[512,8]{1,0} %b), lhs_contracting_dims={1},'
    ' rhs_contracting_dims={0}\n'
    '}\n'
)

BF16_DOT_F32_OUT = BF16_DOT.replace('-> bf16[8,8]', '-> f32[8,8]').replace(
    'ROOT %d = bf16[8,8]{1,0} dot', 'ROOT %d = f32[8,8]{1,0} dot')


def test_shd305_downcast_before_reduce():
    findings = analyze_sharded_hlo(DOWNCAST_REDUCE,
                                   ShardedContext(specimen='fix'))
    assert _rules(findings) == ['SHD305']
    (f,) = findings
    assert f.severity.name == 'ERROR'
    assert 'f32->bf16 downcast feeds `reduce`' in f.message
    assert f.where == 'fix:dgmc_tpu/train/steps.py:90'
    assert '128 element(s)' in f.detail


def test_shd305_f32_accumulation_is_clean():
    assert analyze_sharded_hlo(F32_ACCUM_REDUCE,
                               ShardedContext(specimen='fix')) == []


def test_shd305_bf16_dot_accumulator():
    findings = analyze_sharded_hlo(BF16_DOT,
                                   ShardedContext(specimen='fix'))
    assert _rules(findings) == ['SHD305']
    assert '`dot` accumulates in bf16' in findings[0].message
    # No source metadata on this op: the fallback location must be
    # structural (opcode + ordinal), never the compiler's drifting
    # computation/result names.
    assert findings[0].where == 'fix:dot#0'


def test_shd305_dot_with_f32_out_is_clean():
    """preferred_element_type=f32 shows up as an f32 dot result — the
    contract-compliant spelling must not fire."""
    assert analyze_sharded_hlo(BF16_DOT_F32_OUT,
                               ShardedContext(specimen='fix')) == []


def test_shd305_short_reduction_is_below_threshold():
    short = DOWNCAST_REDUCE.replace('128,128', '128,8').replace(
        'f32[128,128]', 'f32[128,8]')
    assert analyze_sharded_hlo(short,
                               ShardedContext(specimen='fix')) == []


# --- real specimens through the tier driver -----------------------------


@pytest.mark.skipif(len(jax.devices()) < 4, reason='needs 4 devices')
def test_sharded_tier_runs_clean_on_registered_specimens():
    """The registered multi-device specimens compile under their meshes
    and produce ONLY SHD-rule findings (today: none — the repo's
    sharded programs are communication-clean; any future finding lands
    in the baseline as a reviewed SHD entry, never as TRC drift)."""
    from dgmc_tpu.analysis.registry import SpecimenCache
    from dgmc_tpu.analysis.shd_rules import run_sharded_tier
    cache = SpecimenCache()
    findings = run_sharded_tier(cache=cache)
    assert all(f.rule.startswith('SHD') for f in findings)
    assert sorted(cache.stats()) == [
        'parallel.sharded_forward_rows', 'parallel.sharded_topk_cols',
        'parallel.sharded_train_step',
        'parallel.sharded_train_step_pairs2',
        'parallel.streamed_train_step']


@pytest.mark.skipif(len(jax.devices()) < 2, reason='needs 2 devices')
def test_distributed_topk_specimen_schedule_has_its_gather():
    """The parallel/topk.py column-sharded specimen's partitioned HLO
    exposes the candidate all_gather — and it is (by design) far
    smaller than the N_s x N_t matrix it avoids, so SHD302 stays
    quiet."""
    from dgmc_tpu.analysis.hlo_comm import collective_schedule
    from dgmc_tpu.analysis.registry import SpecimenCache, default_specimens
    (spec,) = [s for s in default_specimens()
               if s.name == 'parallel.sharded_topk_cols']
    art = SpecimenCache().artifacts(spec)
    sched = collective_schedule(art.compiled().as_text())
    gathers = [c for c in sched if c.kind == 'all-gather']
    assert gathers, 'candidate merge all_gather missing from schedule'
    assert all(c.nbytes < art.built()['corr_bytes'] for c in gathers)
