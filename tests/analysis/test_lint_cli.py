"""CLI contract: --json report shape, --fail-on policies, baseline
round-trip. (Trace tier is exercised by test_repo_clean; here it is
skipped so the CLI paths stay fast.)"""

import json
import os
import shutil

import pytest

from dgmc_tpu.analysis.lint import RULE_CATALOG, main

FIXTURES = os.path.join(os.path.dirname(__file__), 'fixtures.py')


@pytest.fixture
def bad_tree(tmp_path):
    """A tiny source tree with known source-tier findings."""
    root = tmp_path / 'pkg'
    root.mkdir()
    shutil.copy(FIXTURES, root / 'fixtures.py')
    return str(root)


def _run(args, capsys):
    rc = main(args)
    out = capsys.readouterr().out
    return rc, out


def test_list_rules(capsys):
    rc, out = _run(['--list-rules'], capsys)
    assert rc == 0
    for rule in RULE_CATALOG:
        assert rule in out
    assert {'SHD301', 'SHD302', 'SHD303', 'SHD304',
            'SHD305'} <= set(RULE_CATALOG)


def test_rule_reference_page_enumerates_every_rule():
    """docs/source/modules/lint-rules.rst is the rendered face of the
    catalog — every TRC/SRC/RCP/SHD rule id must appear on it."""
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    page = os.path.join(repo, 'docs', 'source', 'modules',
                        'lint-rules.rst')
    with open(page) as f:
        rst = f.read()
    for rule in RULE_CATALOG:
        assert f'``{rule}``' in rst, f'{rule} missing from lint-rules.rst'


# The baseline-lifecycle tests below each run the full multi-tier
# analysis 2-3 times over the specimen tree (~25-55s apiece on CPU);
# they are tier-2 (-m slow). The fast CLI tests keep every code path
# (filtering, severity, usage errors, concurrency tier) in tier-1.
@pytest.mark.slow
def test_json_report_and_fail_on_new(bad_tree, tmp_path, capsys):
    baseline = str(tmp_path / 'bl.json')
    args = ['--json', '--skip-trace', '--skip-recompile',
            '--skip-sharded',
            '--source-root', bad_tree, '--baseline', baseline]
    rc, out = _run(args + ['--fail-on', 'new'], capsys)
    assert rc == 1
    report = json.loads(out)
    rules = {f['rule'] for f in report['findings']}
    assert {'SRC101', 'SRC102', 'SRC103', 'SRC104'} <= rules
    assert report['summary']['new'] == report['summary']['total'] > 0
    assert report['summary']['suppressed'] == 0
    for f in report['findings']:
        assert f['fingerprint']
        assert f['severity'] in ('error', 'warning', 'info')


@pytest.mark.slow
def test_baseline_roundtrip_suppresses(bad_tree, tmp_path, capsys):
    baseline = str(tmp_path / 'bl.json')
    args = ['--json', '--skip-trace', '--skip-recompile',
            '--skip-sharded',
            '--source-root', bad_tree, '--baseline', baseline]
    rc, _ = _run(args + ['--write-baseline'], capsys)
    assert rc == 0
    rc, out = _run(args + ['--fail-on', 'new'], capsys)
    assert rc == 0
    report = json.loads(out)
    assert report['summary']['new'] == 0
    assert report['summary']['suppressed'] == report['summary']['total'] > 0
    # 'any' still fails on baselined findings; 'none' never fails.
    assert _run(args + ['--fail-on', 'any'], capsys)[0] == 1
    assert _run(args + ['--fail-on', 'none'], capsys)[0] == 0


def test_fail_on_error_ignores_warnings(bad_tree, tmp_path, capsys):
    baseline = str(tmp_path / 'bl.json')
    args = ['--json', '--skip-trace', '--skip-recompile',
            '--skip-sharded',
            '--source-root', bad_tree, '--baseline', baseline]
    # Only warnings (drop the SRC101 ERROR): rc 0 under --fail-on error.
    rc, _ = _run(args + ['--fail-on', 'error',
                         '--rules', 'SRC102,SRC103,SRC104'], capsys)
    assert rc == 0
    # With the ERROR rule kept, it fails.
    rc, _ = _run(args + ['--fail-on', 'error', '--rules', 'SRC101'],
                 capsys)
    assert rc == 1


# Severity filtering runs the multi-tier analysis twice (~23s);
# tier-1 keeps the select/ignore path, which exercises the same
# finding-filter plumbing in one pass.
@pytest.mark.slow
def test_min_severity_filter(bad_tree, tmp_path, capsys):
    baseline = str(tmp_path / 'bl.json')
    args = ['--json', '--skip-trace', '--skip-recompile',
            '--skip-sharded',
            '--source-root', bad_tree, '--baseline', baseline,
            '--fail-on', 'none']
    rc, out = _run(args + ['--min-severity', 'error'], capsys)
    assert rc == 0
    report = json.loads(out)
    assert report['findings']
    assert all(f['severity'] == 'error' for f in report['findings'])


def test_unknown_rule_is_a_usage_error(bad_tree, tmp_path, capsys):
    rc, _ = _run(['--json', '--skip-trace', '--skip-recompile',
                  '--skip-sharded', '--source-root', bad_tree,
                  '--baseline', str(tmp_path / 'bl.json'),
                  '--rules', 'NOPE999'], capsys)
    assert rc == 2


def test_missing_obs_dir_is_a_usage_error(tmp_path, capsys):
    """A vanished obs dir must not silently disable the telemetry
    cross-check the caller asked for."""
    rc, _ = _run(['--json', '--skip-trace', '--skip-source',
                  '--obs-dir', str(tmp_path / 'gone'),
                  '--baseline', str(tmp_path / 'bl.json')], capsys)
    assert rc == 2


# Baseline-lifecycle family like the roundtrip/prune tests below
# (~11s of repeated multi-tier analysis): tier-2.
@pytest.mark.slow
def test_write_baseline_preserves_unanalyzed_tiers(bad_tree, tmp_path,
                                                   capsys):
    """Refreshing the baseline in a smaller environment (skipped tier /
    too few devices) keeps the entries that environment cannot
    reproduce, so CI's bigger run does not see them as 'new'."""
    baseline = str(tmp_path / 'bl.json')
    sharded = {'rule': 'TRC005', 'severity': 'info',
               'where': 'parallel.sharded_train_step:dgmc_tpu/x.py:1',
               'message': 'm', 'fingerprint': 'feedfacefeedface'}
    (tmp_path / 'bl.json').write_text(json.dumps(
        {'version': 2, 'findings': [sharded]}))
    rc, _ = _run(['--skip-trace', '--skip-recompile', '--skip-sharded',
                  '--source-root', bad_tree, '--baseline', baseline,
                  '--write-baseline'], capsys)
    assert rc == 0
    entries = json.loads((tmp_path / 'bl.json').read_text())['findings']
    fps = {e['fingerprint'] for e in entries}
    assert 'feedfacefeedface' in fps, 'skipped-tier entry was dropped'
    assert len(fps) > 1, 'current source findings missing'


def test_explain_prints_what_why_fix(capsys):
    rc, out = _run(['--explain', 'SHD301'], capsys)
    assert rc == 0
    assert 'SHD301' in out
    for section in ('What:', 'Why:', 'Fix:'):
        assert section in out
    # Multiple rules, comma-separated, across tiers.
    rc, out = _run(['--explain', 'TRC004,SHD305'], capsys)
    assert rc == 0
    assert 'TRC004' in out and 'SHD305' in out


def test_explain_unknown_rule_is_a_usage_error(capsys):
    assert _run(['--explain', 'SHD999'], capsys)[0] == 2


def test_select_and_ignore_filtering(bad_tree, tmp_path, capsys):
    baseline = str(tmp_path / 'bl.json')
    args = ['--json', '--skip-trace', '--skip-recompile',
            '--skip-sharded', '--source-root', bad_tree,
            '--baseline', baseline, '--fail-on', 'none']
    rc, out = _run(args + ['--select', 'SRC101,SRC103'], capsys)
    assert rc == 0
    rules = {f['rule'] for f in json.loads(out)['findings']}
    assert rules == {'SRC101', 'SRC103'}
    rc, out = _run(args + ['--ignore', 'SRC101,SRC103'], capsys)
    assert rc == 0
    rules = {f['rule'] for f in json.loads(out)['findings']}
    assert rules and 'SRC101' not in rules and 'SRC103' not in rules
    # select and ignore compose (ignore wins on the intersection).
    rc, out = _run(args + ['--select', 'SRC101,SRC102',
                           '--ignore', 'SRC101'], capsys)
    assert {f['rule'] for f in json.loads(out)['findings']} == {'SRC102'}
    assert _run(args + ['--ignore', 'NOPE1'], capsys)[0] == 2


@pytest.mark.slow
def test_prune_baseline_drops_only_stale_entries(bad_tree, tmp_path,
                                                 capsys):
    """--prune-baseline: entries that stopped reproducing go, entries
    still live stay, entries of un-analyzed tiers are protected — and
    nothing NEW is ever added (that stays a --write-baseline review)."""
    baseline = str(tmp_path / 'bl.json')
    args = ['--skip-trace', '--skip-recompile', '--skip-sharded',
            '--source-root', bad_tree, '--baseline', baseline]
    rc, _ = _run(args + ['--write-baseline'], capsys)
    assert rc == 0
    entries = json.loads((tmp_path / 'bl.json').read_text())['findings']
    n_live = len(entries)
    assert n_live > 0
    # Seed one stale source entry (will not reproduce) and one TRC
    # entry (its tier is skipped in this run -> protected).
    entries.append({'rule': 'SRC103', 'severity': 'warning',
                    'where': 'pkg/gone.py:1', 'message': 'stale',
                    'fingerprint': 'deadbeefdeadbeef'})
    entries.append({'rule': 'TRC005', 'severity': 'info',
                    'where': 'forward_dense:dgmc_tpu/x.py:1',
                    'message': 'm', 'fingerprint': 'feedfacefeedface'})
    (tmp_path / 'bl.json').write_text(json.dumps(
        {'version': 2, 'tool': 'dgmc-lint', 'findings': entries}))
    rc, out = _run(args + ['--prune-baseline'], capsys)
    assert rc == 0
    assert 'pruned 1 stale entry' in out
    fps = {e['fingerprint'] for e in json.loads(
        (tmp_path / 'bl.json').read_text())['findings']}
    assert 'deadbeefdeadbeef' not in fps, 'stale entry kept'
    assert 'feedfacefeedface' in fps, 'skipped-tier entry pruned'
    assert len(fps) == n_live + 1
    # After the prune, the live findings still suppress cleanly.
    assert _run(['--json'] + args + ['--fail-on', 'new'], capsys)[0] == 0


def test_select_skips_unselected_tiers(bad_tree, tmp_path, capsys):
    """--select SRC... must not pay the trace/SHD/sched tiers' specimen
    compiles (the dominant lint cost) for findings the filter would
    drop anyway."""
    rc = main(['--select', 'SRC102', '--source-root', bad_tree,
               '--baseline', str(tmp_path / 'bl.json'),
               '--fail-on', 'none'])
    err = capsys.readouterr().err
    assert rc == 0
    assert 'source tier' in err
    assert 'trace ' not in err, 'trace tier ran despite --select SRC102'
    assert 'sharded-hlo' not in err, 'SHD tier ran despite --select'
    assert 'schedule ' not in err, 'sched tier ran despite --select'


def test_skip_sched_drops_sch_and_mem_rules(bad_tree, tmp_path, capsys):
    """--skip-sched removes BOTH rule families of the schedule &
    liveness tier (SCH and MEM are one pass over the same compiled
    specimens)."""
    from dgmc_tpu.analysis.lint import _rules_analyzed, build_parser
    args = build_parser().parse_args(['--skip-sched'])
    rules = _rules_analyzed(args)
    assert not {r for r in rules if r.startswith(('SCH', 'MEM'))}
    assert {'SHD301', 'TRC001', 'SRC101'} <= rules
    # And the sched-tier rules exist in the catalog for --select.
    assert {'SCH401', 'SCH402', 'SCH403',
            'MEM404', 'MEM405'} <= set(RULE_CATALOG)


@pytest.mark.slow
def test_prune_baseline_ignores_min_severity(bad_tree, tmp_path,
                                             capsys):
    """--prune-baseline --min-severity error must not classify
    still-reproducing warning/info suppressions as stale: severity is a
    report filter, not an analysis boundary."""
    baseline = str(tmp_path / 'bl.json')
    args = ['--skip-trace', '--skip-recompile', '--skip-sharded',
            '--source-root', bad_tree, '--baseline', baseline]
    rc, _ = _run(args + ['--write-baseline'], capsys)
    assert rc == 0
    entries = json.loads((tmp_path / 'bl.json').read_text())['findings']
    assert any(e['severity'] != 'error' for e in entries)
    n_live = len(entries)
    rc, out = _run(args + ['--prune-baseline',
                           '--min-severity', 'error'], capsys)
    assert rc == 0
    assert 'pruned 0 stale entries' in out
    kept = json.loads((tmp_path / 'bl.json').read_text())['findings']
    assert len(kept) == n_live


def test_prune_and_write_are_mutually_exclusive(tmp_path, capsys):
    rc, _ = _run(['--write-baseline', '--prune-baseline',
                  '--baseline', str(tmp_path / 'bl.json')], capsys)
    assert rc == 2


@pytest.fixture
def race_tree(tmp_path):
    """A tiny source tree with a known CON501 finding (and no SRC
    findings)."""
    root = tmp_path / 'racepkg'
    root.mkdir()
    (root / 'racy.py').write_text(
        'import threading\n\n\n'
        'class C:\n'
        '    def __init__(self):\n'
        '        self.n = 0\n'
        '        threading.Thread(target=self._loop).start()\n\n'
        '    def _loop(self):\n'
        '        self.n += 1\n')
    return str(root)


def test_concurrency_tier_through_the_cli(race_tree, tmp_path, capsys):
    args = ['--json', '--skip-trace', '--skip-recompile',
            '--skip-sharded', '--skip-sched', '--source-root', race_tree,
            '--baseline', str(tmp_path / 'bl.json')]
    rc, out = _run(args + ['--fail-on', 'new'], capsys)
    assert rc == 1
    report = json.loads(out)
    assert {f['rule'] for f in report['findings']} == {'CON501'}
    (finding,) = report['findings']
    assert finding['severity'] == 'error'
    assert finding['where'].startswith('racepkg/racy.py:')
    # --skip-concurrency drops the tier (and the finding with it).
    rc, out = _run(args + ['--skip-concurrency', '--fail-on', 'new'],
                   capsys)
    assert rc == 0
    assert json.loads(out)['findings'] == []
    # Tier-aware --select: selecting only CON rules skips the source
    # tier entirely; selecting only SRC rules skips the CON tier.
    rc = main(args[1:] + ['--select', 'CON501', '--fail-on', 'none'])
    err = capsys.readouterr().err
    assert rc == 0
    assert 'concurrency tier' in err and 'source tier' not in err
    rc = main(args[1:] + ['--select', 'SRC101', '--fail-on', 'none'])
    err = capsys.readouterr().err
    assert 'source tier' in err and 'concurrency tier' not in err


def test_skip_concurrency_preserves_baselined_con_entries(
        race_tree, tmp_path, capsys):
    """A --skip-concurrency --write-baseline must not drop reviewed CON
    suppressions (_rules_analyzed is the preservation boundary)."""
    baseline = str(tmp_path / 'bl.json')
    args = ['--skip-trace', '--skip-recompile', '--skip-sharded',
            '--skip-sched', '--source-root', race_tree,
            '--baseline', baseline]
    rc, _ = _run(args + ['--write-baseline'], capsys)
    assert rc == 0
    fps = {e['fingerprint'] for e in json.loads(
        (tmp_path / 'bl.json').read_text())['findings']}
    assert fps, 'CON finding was not recorded'
    rc, _ = _run(args + ['--skip-concurrency', '--write-baseline'],
                 capsys)
    assert rc == 0
    kept = {e['fingerprint'] for e in json.loads(
        (tmp_path / 'bl.json').read_text())['findings']}
    assert fps <= kept, 'skip-concurrency rewrite dropped CON entries'


def test_github_format_annotations(race_tree, bad_tree, tmp_path,
                                   capsys):
    """--format github: one ::error/::warning annotation per NEW
    finding with file= and line= properties; baselined findings are
    not annotated; --json output stays byte-identical to before."""
    baseline = str(tmp_path / 'bl.json')
    args = ['--skip-trace', '--skip-recompile', '--skip-sharded',
            '--skip-sched', '--source-root', race_tree,
            '--baseline', baseline]
    rc, out = _run(args + ['--format', 'github', '--fail-on', 'new'],
                   capsys)
    assert rc == 1
    lines = out.splitlines()
    ann = [ln for ln in lines if ln.startswith('::')]
    assert len(ann) == 1
    assert ann[0].startswith('::error file=racepkg/racy.py,line=')
    assert 'title=dgmc-lint CON501' in ann[0]
    assert '::CON501: ' in ann[0]
    assert lines[-1].startswith('dgmc-lint: 1 finding(s) — 1 new')
    # Baselined findings produce NO annotations (reviewed debt is not
    # re-announced on every PR) but still count in the summary line.
    rc, _ = _run(args + ['--write-baseline'], capsys)
    assert rc == 0
    rc, out = _run(args + ['--format', 'github', '--fail-on', 'new'],
                   capsys)
    assert rc == 0
    assert not [ln for ln in out.splitlines() if ln.startswith('::')]
    assert '1 finding(s) — 0 new, 1 baselined' in out
    # --json unchanged by the new mode; --json + --format github is a
    # usage error rather than a silent pick.
    rc, out = _run(['--json'] + args + ['--fail-on', 'none'], capsys)
    assert rc == 0
    json.loads(out)
    rc, _ = _run(['--json'] + args + ['--format', 'github'], capsys)
    assert rc == 2


def test_github_format_escapes_newlines_and_commas(tmp_path, capsys):
    """Workflow-command escaping: %, CR, LF in messages; a finding in a
    file whose path contains a comma must not break the property
    parser."""
    from io import StringIO
    from dgmc_tpu.analysis.lint import render_github
    report = {
        'new': ['abc'],
        'findings': [{
            'rule': 'CON501', 'severity': 'error', 'fingerprint': 'abc',
            'where': 'pkg/o,dd.py:3',
            'message': 'line one\nline two % done',
        }],
        'summary': {'total': 1, 'new': 1, 'suppressed': 0,
                    'errors': 1, 'warnings': 0, 'infos': 0},
    }
    buf = StringIO()
    render_github(report, stream=buf)
    out = buf.getvalue()
    assert '::error file=pkg/o%2Cdd.py,line=3' in out
    assert 'line one%0Aline two %25 done' in out
    assert '\nline two' not in out


def test_obs_dir_recompile_crosscheck(tmp_path, capsys):
    obs = tmp_path / 'obs'
    obs.mkdir()
    (obs / 'timings.json').write_text(json.dumps({
        'compile': {'events': 40},
        'padding_buckets': [
            {'batch': 8, 'nodes': '32x40', 'edges': '64x80', 'count': 2},
            {'batch': 8, 'nodes': '24x40', 'edges': '64x80', 'count': 1}],
    }))
    rc, out = _run(['--json', '--skip-trace', '--skip-source',
                    '--obs-dir', str(obs),
                    '--baseline', str(tmp_path / 'bl.json'),
                    '--fail-on', 'none'], capsys)
    assert rc == 0
    rules = [f['rule'] for f in json.loads(out)['findings']]
    assert 'RCP201' in rules and 'RCP202' in rules
