"""CLI contract: --json report shape, --fail-on policies, baseline
round-trip. (Trace tier is exercised by test_repo_clean; here it is
skipped so the CLI paths stay fast.)"""

import json
import os
import shutil

import pytest

from dgmc_tpu.analysis.lint import RULE_CATALOG, main

FIXTURES = os.path.join(os.path.dirname(__file__), 'fixtures.py')


@pytest.fixture
def bad_tree(tmp_path):
    """A tiny source tree with known source-tier findings."""
    root = tmp_path / 'pkg'
    root.mkdir()
    shutil.copy(FIXTURES, root / 'fixtures.py')
    return str(root)


def _run(args, capsys):
    rc = main(args)
    out = capsys.readouterr().out
    return rc, out


def test_list_rules(capsys):
    rc, out = _run(['--list-rules'], capsys)
    assert rc == 0
    for rule in RULE_CATALOG:
        assert rule in out


def test_json_report_and_fail_on_new(bad_tree, tmp_path, capsys):
    baseline = str(tmp_path / 'bl.json')
    args = ['--json', '--skip-trace', '--skip-recompile',
            '--source-root', bad_tree, '--baseline', baseline]
    rc, out = _run(args + ['--fail-on', 'new'], capsys)
    assert rc == 1
    report = json.loads(out)
    rules = {f['rule'] for f in report['findings']}
    assert {'SRC101', 'SRC102', 'SRC103', 'SRC104'} <= rules
    assert report['summary']['new'] == report['summary']['total'] > 0
    assert report['summary']['suppressed'] == 0
    for f in report['findings']:
        assert f['fingerprint']
        assert f['severity'] in ('error', 'warning', 'info')


def test_baseline_roundtrip_suppresses(bad_tree, tmp_path, capsys):
    baseline = str(tmp_path / 'bl.json')
    args = ['--json', '--skip-trace', '--skip-recompile',
            '--source-root', bad_tree, '--baseline', baseline]
    rc, _ = _run(args + ['--write-baseline'], capsys)
    assert rc == 0
    rc, out = _run(args + ['--fail-on', 'new'], capsys)
    assert rc == 0
    report = json.loads(out)
    assert report['summary']['new'] == 0
    assert report['summary']['suppressed'] == report['summary']['total'] > 0
    # 'any' still fails on baselined findings; 'none' never fails.
    assert _run(args + ['--fail-on', 'any'], capsys)[0] == 1
    assert _run(args + ['--fail-on', 'none'], capsys)[0] == 0


def test_fail_on_error_ignores_warnings(bad_tree, tmp_path, capsys):
    baseline = str(tmp_path / 'bl.json')
    args = ['--json', '--skip-trace', '--skip-recompile',
            '--source-root', bad_tree, '--baseline', baseline]
    # Only warnings (drop the SRC101 ERROR): rc 0 under --fail-on error.
    rc, _ = _run(args + ['--fail-on', 'error',
                         '--rules', 'SRC102,SRC103,SRC104'], capsys)
    assert rc == 0
    # With the ERROR rule kept, it fails.
    rc, _ = _run(args + ['--fail-on', 'error', '--rules', 'SRC101'],
                 capsys)
    assert rc == 1


def test_min_severity_filter(bad_tree, tmp_path, capsys):
    baseline = str(tmp_path / 'bl.json')
    args = ['--json', '--skip-trace', '--skip-recompile',
            '--source-root', bad_tree, '--baseline', baseline,
            '--fail-on', 'none']
    rc, out = _run(args + ['--min-severity', 'error'], capsys)
    assert rc == 0
    report = json.loads(out)
    assert report['findings']
    assert all(f['severity'] == 'error' for f in report['findings'])


def test_unknown_rule_is_a_usage_error(bad_tree, tmp_path, capsys):
    rc, _ = _run(['--json', '--skip-trace', '--skip-recompile',
                  '--source-root', bad_tree,
                  '--baseline', str(tmp_path / 'bl.json'),
                  '--rules', 'NOPE999'], capsys)
    assert rc == 2


def test_missing_obs_dir_is_a_usage_error(tmp_path, capsys):
    """A vanished obs dir must not silently disable the telemetry
    cross-check the caller asked for."""
    rc, _ = _run(['--json', '--skip-trace', '--skip-source',
                  '--obs-dir', str(tmp_path / 'gone'),
                  '--baseline', str(tmp_path / 'bl.json')], capsys)
    assert rc == 2


def test_write_baseline_preserves_unanalyzed_tiers(bad_tree, tmp_path,
                                                   capsys):
    """Refreshing the baseline in a smaller environment (skipped tier /
    too few devices) keeps the entries that environment cannot
    reproduce, so CI's bigger run does not see them as 'new'."""
    baseline = str(tmp_path / 'bl.json')
    sharded = {'rule': 'TRC005', 'severity': 'info',
               'where': 'parallel.sharded_train_step:dgmc_tpu/x.py:1',
               'message': 'm', 'fingerprint': 'feedfacefeedface'}
    (tmp_path / 'bl.json').write_text(json.dumps(
        {'version': 1, 'findings': [sharded]}))
    rc, _ = _run(['--skip-trace', '--skip-recompile',
                  '--source-root', bad_tree, '--baseline', baseline,
                  '--write-baseline'], capsys)
    assert rc == 0
    entries = json.loads((tmp_path / 'bl.json').read_text())['findings']
    fps = {e['fingerprint'] for e in entries}
    assert 'feedfacefeedface' in fps, 'skipped-tier entry was dropped'
    assert len(fps) > 1, 'current source findings missing'


def test_obs_dir_recompile_crosscheck(tmp_path, capsys):
    obs = tmp_path / 'obs'
    obs.mkdir()
    (obs / 'timings.json').write_text(json.dumps({
        'compile': {'events': 40},
        'padding_buckets': [
            {'batch': 8, 'nodes': '32x40', 'edges': '64x80', 'count': 2},
            {'batch': 8, 'nodes': '24x40', 'edges': '64x80', 'count': 1}],
    }))
    rc, out = _run(['--json', '--skip-trace', '--skip-source',
                    '--obs-dir', str(obs),
                    '--baseline', str(tmp_path / 'bl.json'),
                    '--fail-on', 'none'], capsys)
    assert rc == 0
    rules = [f['rule'] for f in json.loads(out)['findings']]
    assert 'RCP201' in rules and 'RCP202' in rules
