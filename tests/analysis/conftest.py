"""The analyzer's donation rule compiles donating multi-device programs
(the sharded train-step specimen) — the exact configuration whose
persistent-cache round-trip is broken on jax 0.4.37 (see
tests/parallel/conftest.py for the root cause). Cache hits there could
make TRC004 flicker (or hand back an executable with broken aliasing),
so the analysis tests opt out of the persistent cache the same way."""

import jax
import pytest


@pytest.fixture(autouse=True)
def _no_persistent_compile_cache():
    from jax._src import compilation_cache

    prev = jax.config.jax_enable_compilation_cache
    jax.config.update('jax_enable_compilation_cache', False)
    compilation_cache.reset_cache()  # un-latch is_cache_used
    try:
        yield
    finally:
        jax.config.update('jax_enable_compilation_cache', prev)
        compilation_cache.reset_cache()
