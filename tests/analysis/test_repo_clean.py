"""The full repo lint matches the committed baseline, and the probe-free
train step carries zero host callbacks (the PR 3 byte-identical-HLO
guarantee, as a static check)."""

import os

import jax
import pytest

from dgmc_tpu.analysis import (SpecimenCache, callback_equations,
                               lint_concurrency_paths, load_baseline,
                               lint_source_paths, run_sched_tier,
                               run_sharded_tier, run_trace_tier,
                               split_by_baseline)
from dgmc_tpu.analysis.jaxpr_rules import TraceContext, analyze_closed_jaxpr
from dgmc_tpu.analysis.registry import default_specimens, probes_forced_off

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
BASELINE = os.path.join(REPO, 'lint-baseline.json')


# The full two-tier repo lint (~31s) — CI runs the identical check as
# its own dgmc-lint step, so tier-1 need not repeat it.
@pytest.mark.slow
def test_repo_lint_matches_committed_baseline():
    """No finding outside the reviewed ledger — the exact check CI runs
    (``dgmc-lint --fail-on new``): source AND concurrency tiers over
    the CLI's full root set (package + repo-root bench drivers +
    benchmarks/), plus trace, sharded, and schedule/liveness tiers on
    one shared specimen cache."""
    from dgmc_tpu.analysis.lint import _source_roots, build_parser
    baseline = load_baseline(BASELINE)
    assert baseline, f'missing committed baseline at {BASELINE}'
    roots = _source_roots(build_parser().parse_args([]))
    assert any(r.endswith('dgmc_tpu') for r in roots)
    assert any(r.endswith('serve_bench.py') for r in roots), (
        'bench drivers missing from the default scan roots')
    cache = SpecimenCache()
    findings = (lint_source_paths(roots) + lint_concurrency_paths(roots)
                + run_trace_tier(cache=cache)
                + run_sharded_tier(cache=cache)
                + run_sched_tier(cache=cache))
    new, suppressed = split_by_baseline(findings, baseline)
    assert not new, (
        'findings not in lint-baseline.json (fix them or re-run '
        '`dgmc-lint --write-baseline` after review): '
        + '; '.join(f'{f.rule} {f.where}: {f.message}' for f in new))
    assert suppressed, 'baseline matched nothing — ledger is stale'


def _train_step_jaxpr():
    (spec,) = [s for s in default_specimens()
               if s.name == 'train_step_dense']
    built = spec.build()
    return jax.make_jaxpr(built['fn'])(*built['args'])


def test_probe_free_train_step_has_zero_callback_equations():
    from dgmc_tpu.obs import probes
    assert not probes.enabled()
    with probes_forced_off():
        closed = _train_step_jaxpr()
    assert callback_equations(closed) == []
    assert analyze_closed_jaxpr(
        closed, TraceContext(specimen='train_step_dense')) == [
        f for f in analyze_closed_jaxpr(
            closed, TraceContext(specimen='train_step_dense'))
        if f.rule == 'TRC005'], 'only the known scatter sites may fire'


def test_probe_enabled_train_step_is_flagged():
    """Positive control: with probes on, the same specimen DOES lower
    callbacks — and TRC003 reports every site."""
    from dgmc_tpu.obs import probes
    with probes.activated(probes.ProbeLog()):
        closed = _train_step_jaxpr()
    hits = callback_equations(closed)
    assert hits, 'probes enabled but no callbacks lowered'
    findings = analyze_closed_jaxpr(
        closed, TraceContext(specimen='train_step_dense'))
    assert any(f.rule == 'TRC003' for f in findings)
