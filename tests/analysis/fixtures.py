"""Deliberately-defective functions — one per analyzer defect class.

Each fixture is the smallest program exhibiting exactly one hazard, so
the golden tests can assert that each defect class is detected by its
intended rule AND by no other (a fixture tripping two rules means a rule
lost precision).

The source-level fixtures at the bottom are never executed — they exist
to be *parsed* by the ast tier. Do not "fix" them.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

# ---------------------------------------------------------------------------
# Trace-tier fixtures
# ---------------------------------------------------------------------------

#: 600*600*4 bytes = 1.37 MiB — above the default TRC002 threshold.
BIG_TABLE = np.ones((600, 600), np.float32)


def dtype_drift(x):
    """TRC001: a float64 scalar promotes the whole product to f64 (trace
    under ``jax.experimental.enable_x64`` — with x64 off, jax truncates
    the promotion and the hazard is masked)."""
    return x * np.float64(2.0)


def giant_constant(x):
    """TRC002: closes over a >1 MiB table; it constant-folds into every
    executable instead of riding in as an argument."""
    return x @ jnp.asarray(BIG_TABLE)


def leaked_callback(x):
    """TRC003: a host callback with no trace-time gate — fences
    device->host every step even when nobody listens."""
    jax.debug.callback(lambda v: None, jnp.sum(x))
    return x * 2.0


def dropped_donation(x):
    """TRC004: reduces the donated ``[N, N]`` input to a scalar — no
    output matches the donated buffer, so the donation silently degrades
    to a copy (lowering warns 'donated buffers were not usable')."""
    return jnp.sum(x)


def big_sort(x):
    """TRC006: a full sort over a large axis where a top-k selection was
    intended."""
    return jnp.sort(x, axis=-1)[..., -8:]


# ---------------------------------------------------------------------------
# Source-tier fixtures (parsed, never run)
# ---------------------------------------------------------------------------


class TracerHoarder:
    """SRC101: the jitted method stores a traced value on ``self``."""

    @jax.jit
    def step(self, x):
        self.last = x          # noqa: B003  — the leak under test
        return x * 2.0


@functools.partial(jax.jit, static_argnums=(1,))
def unhashable_static(x, cfg=[1, 2]):  # noqa: B006 — SRC104 under test
    """SRC104: static args are jit cache keys; the mutable default is
    unhashable the first time it is actually used."""
    return x * cfg[0]


@jax.jit
def host_sync(x):
    """SRC102: concretization inside jitted code."""
    scale = float(x)
    return x * scale


def jit_factory_in_loop(fns):
    """SRC103: a fresh jit wrapper (and compile cache) per iteration."""
    out = []
    for f in fns:
        out.append(jax.jit(f))
    return out
