"""Golden detection tests: each trace-tier defect class fires exactly
its intended rule, and clean programs fire nothing."""

import jax
import jax.numpy as jnp

from dgmc_tpu.analysis import (analyze_closed_jaxpr, analyze_donation,
                               callback_equations)
from dgmc_tpu.analysis.jaxpr_rules import TraceContext
from tests.analysis import fixtures


def _rules(findings):
    return sorted({f.rule for f in findings})


def _analyze(fn, *args, **ctx_kw):
    closed = jax.make_jaxpr(fn)(*args)
    return analyze_closed_jaxpr(closed, TraceContext(specimen='fixture',
                                                     **ctx_kw))


def test_dtype_drift_fires_trc001_only():
    from jax.experimental import enable_x64
    with enable_x64():
        findings = _analyze(fixtures.dtype_drift,
                            jnp.ones((4,), jnp.float32))
    assert _rules(findings) == ['TRC001']
    f = findings[0]
    assert 'float64' in f.message
    assert f.where.startswith('fixture:')
    assert 'fixtures.py' in f.where


def test_dtype_drift_masked_without_x64_is_clean():
    # With x64 off jax truncates the promotion — nothing to flag (and
    # nothing false-positive about the f32 math that remains).
    findings = _analyze(fixtures.dtype_drift, jnp.ones((4,), jnp.float32))
    assert findings == []


def test_giant_constant_fires_trc002_only():
    findings = _analyze(fixtures.giant_constant, jnp.ones((600,)))
    assert _rules(findings) == ['TRC002']
    assert '(600, 600)' in findings[0].message


def test_giant_constant_respects_threshold():
    findings = _analyze(fixtures.giant_constant, jnp.ones((600,)),
                        const_bytes=16 << 20)
    assert findings == []


def test_leaked_callback_fires_trc003_only():
    findings = _analyze(fixtures.leaked_callback, jnp.ones((8,)))
    assert _rules(findings) == ['TRC003']
    assert 'debug_callback' in findings[0].message


def test_callback_rule_respects_expectation_flag():
    findings = _analyze(fixtures.leaked_callback, jnp.ones((8,)),
                        expect_no_callbacks=False)
    assert findings == []


def test_dropped_donation_fires_trc004_only():
    findings = analyze_donation(fixtures.dropped_donation,
                                (jnp.ones((64, 64)),),
                                donate_argnums=(0,), specimen='fixture')
    assert _rules(findings) == ['TRC004']
    assert findings[0].severity.name == 'ERROR'


def test_retained_donation_is_clean():
    findings = analyze_donation(lambda x: x * 2.0, (jnp.ones((64, 64)),),
                                donate_argnums=(0,), specimen='fixture')
    assert findings == []


def test_big_sort_fires_trc006_only():
    findings = _analyze(fixtures.big_sort, jnp.ones((2, 8192)),
                        sort_dim=4096)
    assert _rules(findings) == ['TRC006']


def test_small_sort_is_clean():
    findings = _analyze(fixtures.big_sort, jnp.ones((2, 64)))
    assert findings == []


def test_scatter_without_unique_indices_fires_trc005():
    def scatter_add(x, idx, upd):
        return x.at[idx].add(upd)

    findings = _analyze(scatter_add, jnp.zeros((16,)),
                        jnp.array([1, 2, 2]), jnp.ones((3,)))
    assert _rules(findings) == ['TRC005']
    # One finding per site, occurrence count in detail.
    assert len(findings) == 1
    assert '1 equation(s)' in findings[0].detail


def test_clean_program_produces_no_findings():
    def clean(x, y):
        return jnp.tanh(x) @ y

    findings = _analyze(clean, jnp.ones((8, 8)), jnp.ones((8, 4)))
    assert findings == []


def test_rules_walk_nested_jaxprs():
    """Hazards inside scan/pjit sub-jaxprs are still found."""
    def nested(x):
        def body(c, _):
            jax.debug.callback(lambda v: None, jnp.sum(c))
            return c * 2.0, None
        y, _ = jax.lax.scan(body, x, None, length=3)
        return y

    findings = _analyze(nested, jnp.ones((4,)))
    assert _rules(findings) == ['TRC003']


def test_callback_equations_reports_provenance():
    closed = jax.make_jaxpr(fixtures.leaked_callback)(jnp.ones((4,)))
    hits = callback_equations(closed)
    assert len(hits) == 1
    name, prov = hits[0]
    assert name == 'debug_callback'
    assert 'fixtures.py' in prov
