"""Line-number-independent fingerprints (baseline v2): relocation keeps
a suppression, changed code releases it, and the one-shot migration off
v1 ledgers is enforced.

PRs 6 and 9 each churned 3 TRC005 baseline entries on pure line
relocations — edits ABOVE the finding that moved its line without
touching the flagged statement. The v2 fingerprint hashes the
line-stripped ``where`` plus a normalized context snippet (source line
text / HLO op kind+shape) instead, pinned here end to end.
"""

import json
import os
import textwrap

import pytest

from dgmc_tpu.analysis.findings import (Finding, Severity, load_baseline,
                                        write_baseline)
from dgmc_tpu.analysis.lint import main as lint_main
from dgmc_tpu.analysis.source_rules import lint_source_tree

SRC = textwrap.dedent('''\
    import jax

    def build(fns):
        out = []
        for f in fns:
            out.append(jax.jit(f))
        return out
''')


def test_fingerprint_ignores_where_line_number():
    a = Finding(rule='TRC005', severity=Severity.INFO,
                where='spec:dgmc_tpu/ops/graph.py:101', message='m',
                context='return jax.ops.segment_sum(m, r)')
    b = Finding(rule='TRC005', severity=Severity.INFO,
                where='spec:dgmc_tpu/ops/graph.py:202', message='m',
                context='return jax.ops.segment_sum(m, r)')
    assert a.fingerprint == b.fingerprint
    # Different context at the same file = a different finding.
    c = Finding(rule='TRC005', severity=Severity.INFO,
                where='spec:dgmc_tpu/ops/graph.py:101', message='m',
                context='return other_scatter(m, r)')
    assert c.fingerprint != a.fingerprint


def test_moving_a_source_finding_keeps_its_fingerprint(tmp_path):
    """End to end: inserting lines ABOVE a finding relocates it without
    churning the fingerprint — the exact edit class that invalidated 3
    baseline entries in PRs 6 and 9."""
    root_a = tmp_path / 'a' / 'pkg'
    root_b = tmp_path / 'b' / 'pkg'
    for root in (root_a, root_b):
        root.mkdir(parents=True)
    (root_a / 'mod.py').write_text(SRC)
    (root_b / 'mod.py').write_text('# a new comment\n# another\n' + SRC)
    (fa,) = lint_source_tree(str(root_a))
    (fb,) = lint_source_tree(str(root_b))
    assert fa.rule == fb.rule == 'SRC103'
    assert fa.where != fb.where                # the line DID move
    assert fa.context == fb.context == 'out.append(jax.jit(f))'
    assert fa.fingerprint == fb.fingerprint


def test_editing_the_flagged_line_releases_the_fingerprint(tmp_path):
    root_a = tmp_path / 'a' / 'pkg'
    root_b = tmp_path / 'b' / 'pkg'
    for root in (root_a, root_b):
        root.mkdir(parents=True)
    (root_a / 'mod.py').write_text(SRC)
    (root_b / 'mod.py').write_text(
        SRC.replace('out.append(jax.jit(f))',
                    'out.append(jax.jit(f, donate_argnums=(0,)))'))
    (fa,) = lint_source_tree(str(root_a))
    (fb,) = lint_source_tree(str(root_b))
    assert fa.where == fb.where                # same line number...
    assert fa.fingerprint != fb.fingerprint    # ...different statement


def test_baseline_roundtrip_suppresses_across_relocation(tmp_path):
    """The CLI path: baseline written against tree A suppresses the
    relocated finding in tree B with zero new findings."""
    root_a = tmp_path / 'a' / 'pkg'
    root_b = tmp_path / 'b' / 'pkg'
    for root in (root_a, root_b):
        root.mkdir(parents=True)
    (root_a / 'mod.py').write_text(SRC)
    (root_b / 'mod.py').write_text('# moved\n' * 7 + SRC)
    baseline = str(tmp_path / 'bl.json')
    args = ['--skip-trace', '--skip-recompile', '--skip-sharded',
            '--skip-sched', '--baseline', baseline]
    assert lint_main(args + ['--source-root', str(root_a),
                             '--write-baseline']) == 0
    assert lint_main(args + ['--source-root', str(root_b),
                             '--fail-on', 'new']) == 0


def test_v1_baseline_check_is_a_migration_error(tmp_path, capsys):
    """Checking against a legacy line-hashed ledger must not silently
    un-suppress everything — it exits 2 naming the migration."""
    baseline = tmp_path / 'bl.json'
    baseline.write_text(json.dumps({
        'version': 1, 'tool': 'dgmc-lint',
        'findings': [{'rule': 'TRC005', 'severity': 'info',
                      'where': 'x:dgmc_tpu/y.py:1', 'message': 'm',
                      'fingerprint': 'deadbeefdeadbeef'}]}))
    with pytest.raises(ValueError, match='--write-baseline'):
        load_baseline(str(baseline))
    assert load_baseline(str(baseline), migrate=True)
    rc = lint_main(['--skip-trace', '--skip-recompile', '--skip-sharded',
                    '--skip-sched', '--skip-source',
                    '--baseline', str(baseline), '--fail-on', 'new'])
    assert rc == 2
    assert '--write-baseline' in capsys.readouterr().err


def test_write_baseline_migrates_v1_to_v2(tmp_path):
    """The one-shot migration: --write-baseline over a v1 ledger
    produces a v2 file whose re-recorded findings carry context
    fingerprints."""
    root = tmp_path / 'pkg'
    root.mkdir()
    (root / 'mod.py').write_text(SRC)
    baseline = tmp_path / 'bl.json'
    baseline.write_text(json.dumps({
        'version': 1, 'tool': 'dgmc-lint', 'findings': []}))
    rc = lint_main(['--skip-trace', '--skip-recompile', '--skip-sharded',
                    '--skip-sched', '--source-root', str(root),
                    '--baseline', str(baseline), '--write-baseline'])
    assert rc == 0
    data = json.loads(baseline.read_text())
    assert data['version'] == 2
    assert data['findings']
    assert all(e.get('context') for e in data['findings'])


def test_committed_baseline_is_v2_with_contexts():
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    path = os.path.join(repo, 'lint-baseline.json')
    data = json.loads(open(path).read())
    assert data['version'] == 2
    by_rule = {}
    for e in data['findings']:
        by_rule[e['rule']] = by_rule.get(e['rule'], 0) + 1
    assert by_rule == {
        'TRC005': 21,       # the PR 11 migration's reviewed scatters
        'CON501': 1,        # watchdog dump_count: signal path stays
                            # lock-free by design
        'CON503': 15,       # bench-driver in-place artifact writes
        'SRC103': 2,        # psi2_micro's deliberate jit-per-variant
    }, 'the reviewed-debt ledger changed composition — re-triage'
    assert all(e.get('context') for e in data['findings'])


def test_write_baseline_helper_emits_v2(tmp_path):
    path = str(tmp_path / 'bl.json')
    payload = write_baseline(path, [Finding(
        rule='SRC103', severity=Severity.WARNING, where='a.py:3',
        message='m', context='jit(f)')])
    assert payload['version'] == 2
    assert load_baseline(path)


def test_identical_duplicate_statements_get_distinct_fingerprints(
        tmp_path):
    """A copy-pasted duplicate of a baselined violation must NOT ride
    the original's suppression: same rule/file/message/context gets an
    occurrence ordinal, and the first occurrence's fingerprint stays
    stable (relocation-safe) while the duplicate reports as new."""
    root_a = tmp_path / 'a' / 'pkg'
    root_b = tmp_path / 'b' / 'pkg'
    for root in (root_a, root_b):
        root.mkdir(parents=True)
    (root_a / 'mod.py').write_text(SRC)
    dup = SRC.replace('        out.append(jax.jit(f))\n',
                      '        out.append(jax.jit(f))\n'
                      '        out.append(jax.jit(f))\n')
    assert dup != SRC
    (root_b / 'mod.py').write_text(dup)
    (fa,) = lint_source_tree(str(root_a))
    fb1, fb2 = lint_source_tree(str(root_b))
    assert fb1.fingerprint != fb2.fingerprint
    assert fb2.context.endswith('#2')
    assert fa.fingerprint == fb1.fingerprint   # original stays baselined
    # CLI path: baseline from the single-occurrence tree suppresses one
    # and reports exactly the duplicate as new.
    baseline = str(tmp_path / 'bl.json')
    args = ['--skip-trace', '--skip-recompile', '--skip-sharded',
            '--skip-sched', '--baseline', baseline]
    assert lint_main(args + ['--source-root', str(root_a),
                             '--write-baseline']) == 0
    assert lint_main(args + ['--source-root', str(root_b),
                             '--fail-on', 'new']) == 1


def test_prune_baseline_refuses_v1_ledger(tmp_path, capsys):
    """--prune-baseline cannot re-record findings, so against a v1
    ledger it must refuse (rc 2) instead of classifying every reviewed
    entry as stale and deleting the whole ledger."""
    baseline = tmp_path / 'bl.json'
    original = {'version': 1, 'tool': 'dgmc-lint',
                'findings': [{'rule': 'SRC103', 'severity': 'warning',
                              'where': 'pkg/mod.py:6', 'message': 'm',
                              'fingerprint': 'deadbeefdeadbeef'}]}
    baseline.write_text(json.dumps(original))
    rc = lint_main(['--skip-trace', '--skip-recompile', '--skip-sharded',
                    '--skip-sched', '--skip-source',
                    '--baseline', str(baseline), '--prune-baseline'])
    assert rc == 2
    assert '--write-baseline' in capsys.readouterr().err
    assert json.loads(baseline.read_text()) == original, \
        'refused prune must leave the ledger untouched'


def test_partial_migration_warns_about_preserved_v1_entries(tmp_path,
                                                           capsys):
    """Migrating from an environment that skips a tier preserves that
    tier's v1 entries with fingerprints that can never match again —
    the migration must SAY so, or CI breaks on the next push."""
    root = tmp_path / 'pkg'
    root.mkdir()
    (root / 'mod.py').write_text(SRC)
    baseline = tmp_path / 'bl.json'
    baseline.write_text(json.dumps({
        'version': 1, 'tool': 'dgmc-lint',
        'findings': [{'rule': 'TRC005', 'severity': 'info',
                      'where': 'forward_dense:dgmc_tpu/x.py:1',
                      'message': 'm',
                      'fingerprint': 'feedfacefeedface'}]}))
    rc = lint_main(['--skip-trace', '--skip-recompile', '--skip-sharded',
                    '--skip-sched', '--source-root', str(root),
                    '--baseline', str(baseline), '--write-baseline'])
    assert rc == 0
    err = capsys.readouterr().err
    assert 'WARNING' in err and 'legacy fingerprints' in err
    # A clean v2->v2 refresh with the same skips must NOT warn.
    rc = lint_main(['--skip-trace', '--skip-recompile', '--skip-sharded',
                    '--skip-sched', '--source-root', str(root),
                    '--baseline', str(baseline), '--write-baseline'])
    assert rc == 0
    assert 'WARNING' not in capsys.readouterr().err
