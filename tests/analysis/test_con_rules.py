"""Concurrency tier: golden fixtures (one per CON rule), clean
controls, in-repo positive/negative models, and the model's precision
decisions (RMW-only CON501, linear acquire/release CON502 tracking,
tmp+rename CON503 exemptions)."""

import ast
import os
import textwrap

import pytest

from dgmc_tpu.analysis.concurrency import build_module_model
from dgmc_tpu.analysis.con_rules import (lint_concurrency_file,
                                         lint_concurrency_paths,
                                         lint_concurrency_tree)
from dgmc_tpu.analysis.findings import Severity
from dgmc_tpu.analysis.source_rules import lint_source_file

FIXTURES = os.path.join(os.path.dirname(__file__), 'fixtures_con')
REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _fixture(name):
    return os.path.join(FIXTURES, name)


def _lint_src(tmp_path, code):
    p = tmp_path / 'mod.py'
    p.write_text(textwrap.dedent(code))
    return lint_concurrency_file(str(p), rel='mod.py')


# ---------------------------------------------------------------------------
# Golden fixtures: each module trips EXACTLY its rule, and no SRC rule.
# ---------------------------------------------------------------------------

GOLDEN = [
    ('con501_unlocked_counter.py', 'CON501', Severity.ERROR),
    ('con502_lock_inversion.py', 'CON502', Severity.ERROR),
    ('con503_bare_write.py', 'CON503', Severity.WARNING),
    ('con504_signal_lock.py', 'CON504', Severity.ERROR),
    ('con505_unbounded_log.py', 'CON505', Severity.WARNING),
]


@pytest.mark.parametrize('name,rule,severity', GOLDEN,
                         ids=[g[1] for g in GOLDEN])
def test_golden_fixture_trips_exactly_its_rule(name, rule, severity):
    found = lint_concurrency_file(_fixture(name))
    assert found, f'{name} produced no findings'
    assert {f.rule for f in found} == {rule}
    assert all(f.severity == severity for f in found)
    # Every finding carries the v2 context snippet (line-independent
    # fingerprints) and a location inside the fixture.
    for f in found:
        assert f.context
        assert name in f.where
    # The fixture is clean under the source tier: detected by exactly
    # this rule across ALL tiers that scan source.
    assert lint_source_file(_fixture(name)) == []


def test_clean_controls_are_silent():
    for name in ('clean_controls.py', '__init__.py'):
        assert lint_concurrency_file(_fixture(name)) == []
        assert lint_source_file(_fixture(name)) == []


def test_tree_and_paths_drivers_cover_the_fixture_dir():
    by_tree = lint_concurrency_tree(FIXTURES)
    assert {f.rule for f in by_tree} == {'CON501', 'CON502', 'CON503',
                                         'CON504', 'CON505'}
    # The multi-root driver accepts bare files and reports basenames
    # (how repo-root bench drivers are addressed).
    one = lint_concurrency_paths([_fixture('con501_unlocked_counter.py')])
    assert len(one) == 1
    assert one[0].where.startswith('con501_unlocked_counter.py:')


# ---------------------------------------------------------------------------
# In-repo models: the code the rules were calibrated against.
# ---------------------------------------------------------------------------

def _repo_findings(relpath):
    return lint_concurrency_file(os.path.join(REPO, relpath),
                                 rel=relpath)


def test_streaming_histogram_is_the_con501_clean_control():
    """obs/live.py locks observe() and snapshot() — the in-repo
    positive model CON501 must stay silent on (satellite: its
    thread-safety is pinned by the hammer test in tests/obs)."""
    rules = {f.rule for f in _repo_findings('dgmc_tpu/obs/live.py')}
    assert 'CON501' not in rules
    assert 'CON505' not in rules


def test_watchdog_signal_path_is_the_con504_clean_control():
    """obs/watchdog.py's _on_signal is lock-free by contract (cached
    context, dump(use_locks=False)) — the positive model CON504 must
    not flag."""
    rules = {f.rule for f in _repo_findings('dgmc_tpu/obs/watchdog.py')}
    assert 'CON504' not in rules
    assert 'CON503' not in rules  # dump() writes tmp+os.replace


def test_engine_sequential_locks_are_not_an_inversion():
    """serve/engine.py takes _stats_lock, releases, acquires _lock,
    releases in a finally, then takes _stats_lock again — sequential,
    never nested. The linear acquire/release tracking must not read it
    as a CON502 pair."""
    rules = {f.rule for f in _repo_findings('dgmc_tpu/serve/engine.py')}
    assert 'CON502' not in rules


def test_shadow_auditor_counters_lint_clean_after_fix():
    """Regression pin for the genuine finding this tier was built on:
    ShadowAuditor.audited/errors are now incremented under _cond (like
    dropped always was) — CON501 silent on serve/audit.py."""
    assert _repo_findings('dgmc_tpu/serve/audit.py') == []


def test_atomic_writer_is_the_con503_clean_control():
    assert not any(f.rule == 'CON503'
                   for f in _repo_findings('dgmc_tpu/utils/io.py'))


# ---------------------------------------------------------------------------
# Model precision decisions.
# ---------------------------------------------------------------------------

def test_con501_requires_rmw_not_plain_rebind(tmp_path):
    """Plain attribute rebinding from a thread is exempt (STORE_ATTR is
    atomic under the GIL; the watchdog's cache refreshes rely on it) —
    only read-modify-write forms fire."""
    found = _lint_src(tmp_path, '''
        import threading

        class C:
            def __init__(self):
                self.cache = None
                self.n = 0
                threading.Thread(target=self._loop).start()

            def _loop(self):
                self.cache = 'fresh'       # rebind: exempt
                self.n = self.n + 1        # RMW spelled as Assign: fires
    ''')
    assert [f.rule for f in found] == ['CON501']
    assert 'self.n' in found[0].message


def test_con501_any_locked_write_site_silences(tmp_path):
    """One guarded write means the class HAS a locking story for the
    attribute; mixed-discipline is out of scope for an error gate."""
    found = _lint_src(tmp_path, '''
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
                threading.Thread(target=self._loop).start()

            def _loop(self):
                self.n += 1

            def bump(self):
                with self._lock:
                    self.n += 1
    ''')
    assert not any(f.rule == 'CON501' for f in found)


def test_con501_reaches_through_self_calls_and_timers(tmp_path):
    """The entry closure follows self.<m>() from the entry method, and
    Timer callbacks are entries too."""
    found = _lint_src(tmp_path, '''
        import threading

        class C:
            def __init__(self):
                self.fired = 0
                threading.Timer(1.0, self._tick).start()

            def _tick(self):
                self._bump()

            def _bump(self):
                self.fired += 1
    ''')
    assert [f.rule for f in found] == ['CON501']
    assert '_bump' in found[0].message


def test_con501_http_handler_methods_are_entries(tmp_path):
    found = _lint_src(tmp_path, '''
        class Handler:
            hits = None

            def __init__(self):
                self.hits = 0

            def do_GET(self):
                self.hits += 1
    ''')
    assert [f.rule for f in found] == ['CON501']


def test_con502_one_call_level_deep(tmp_path):
    """An inversion split across a self-call is still found: holder of
    B calls a method that takes A, while another path nests A then B."""
    found = _lint_src(tmp_path, '''
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def forward(self):
                with self._a:
                    with self._b:
                        pass

            def backward(self):
                with self._b:
                    self._take_a()

            def _take_a(self):
                with self._a:
                    pass
    ''')
    assert [f.rule for f in found] == ['CON502']


def test_con502_sequential_acquire_release_is_clean(tmp_path):
    """The engine.match idiom: acquire, release in a finally, THEN take
    the other lock — linear statement-order tracking sees no nesting."""
    found = _lint_src(tmp_path, '''
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                self._a.acquire()
                try:
                    pass
                finally:
                    self._a.release()
                with self._b:
                    pass

            def two(self):
                with self._b:
                    pass
                with self._a:
                    pass
    ''')
    assert found == []


def test_con503_tmp_rename_and_append_are_exempt(tmp_path):
    found = _lint_src(tmp_path, '''
        import json
        import os

        def atomic(path, payload):
            scratch = path + '.tmp'
            with open(scratch, 'w') as f:
                json.dump(payload, f)
            os.replace(scratch, path)

        def appender(path, line):
            with open(path, 'a') as f:
                f.write(line)

        def torn(path, payload):
            with open(path, 'w') as f:
                json.dump(payload, f)
    ''')
    assert [f.rule for f in found] == ['CON503']
    assert ':15' in found[0].where or 'torn' in found[0].message


def test_con504_flags_direct_body_only(tmp_path):
    """Only the handler's own body is judged — work it delegates to a
    method (the watchdog's dump(use_locks=False)) is that method's
    business. Lambdas registered inline are judged too."""
    found = _lint_src(tmp_path, '''
        import signal
        import threading

        LOCK = threading.Lock()

        def handler(signum, frame):
            helper()                    # delegation: not judged here

        def helper():
            with LOCK:
                print('deep')           # not in the handler body

        signal.signal(signal.SIGTERM, handler)
        signal.signal(signal.SIGINT, lambda s, f: print('bye'))
    ''')
    assert [f.rule for f in found] == ['CON504']
    assert '<lambda>' in found[0].message


def test_con505_deque_maxlen_and_len_check_are_exempt(tmp_path):
    found = _lint_src(tmp_path, '''
        import collections
        import threading

        class C:
            def __init__(self):
                self.ring = collections.deque(maxlen=64)
                self.capped = {}
                self.leak = []
                threading.Thread(target=self._loop).start()

            def _loop(self):
                self.ring.append(1)
                if len(self.capped) < 100:
                    self.capped['k'] = 1
                self.leak.append(1)
    ''')
    assert [f.rule for f in found] == ['CON505']
    assert 'self.leak' in found[0].message


def test_unparseable_file_is_the_source_tiers_problem(tmp_path):
    p = tmp_path / 'broken.py'
    p.write_text('def f(:\n')
    assert lint_concurrency_file(str(p)) == []
    assert [f.rule for f in lint_source_file(str(p))] == ['SRC100']


def test_refuses_bytecode(tmp_path):
    pyc = tmp_path / '__pycache__'
    pyc.mkdir()
    target = pyc / 'mod.cpython-311.pyc'
    target.write_bytes(b'\x00')
    with pytest.raises(ValueError, match='bytecode'):
        lint_concurrency_file(str(target))


def test_module_model_shape():
    """The model itself: entries, closure, lock attrs, and lock-order
    edges are what the rules believe they are."""
    tree = ast.parse(textwrap.dedent('''
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition()
                self.jobs = []
                threading.Thread(target=self._run, daemon=True).start()

            def _run(self):
                self._step()

            def _step(self):
                with self._lock:
                    with self._cond:
                        self.jobs.append(1)
    '''))
    model = build_module_model(tree)
    (cls,) = model.classes
    assert cls.lock_attrs == {'_lock', '_cond'}
    assert set(cls.entry_closure) == {'_run', '_step'}
    assert cls.entry_closure['_step'][1] == '_run'
    assert ('_lock', '_cond') in cls.lock_edges
    assert ('_cond', '_lock') not in cls.lock_edges
    assert cls.container_attrs == {'jobs': False}
