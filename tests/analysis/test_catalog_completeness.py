"""Catalog completeness: drift between the rule registry, the CLI
surfaces, and the docs reference page is impossible.

Every rule id any analysis module can EMIT must (a) have a catalog
entry with a tier and non-empty what/why/fix, (b) appear in
``--list-rules``, (c) render through ``--explain``, and (d) appear on
docs/source/modules/lint-rules.rst under its tier section. Conversely
the catalog must not carry rules nothing can emit."""

import os
import re

from dgmc_tpu.analysis.catalog import (RULE_CATALOG, RULES, TIERS,
                                       explain_rule)
from dgmc_tpu.analysis.lint import main

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
ANALYSIS_DIR = os.path.join(REPO, 'dgmc_tpu', 'analysis')
RST = os.path.join(REPO, 'docs', 'source', 'modules', 'lint-rules.rst')

_RULE_ID = re.compile(r"'([A-Z]{3}\d{3})'")


def _emitted_rule_ids():
    """Rule-id string literals across every analysis module except the
    catalog itself (which registers, not emits)."""
    out = set()
    for fn in sorted(os.listdir(ANALYSIS_DIR)):
        if not fn.endswith('.py') or fn == 'catalog.py':
            continue
        with open(os.path.join(ANALYSIS_DIR, fn)) as f:
            out |= set(_RULE_ID.findall(f.read()))
    return out


def test_every_emitted_rule_is_cataloged_and_vice_versa():
    emitted = _emitted_rule_ids()
    assert emitted, 'rule-literal scan found nothing — regex rotted?'
    missing = emitted - set(RULES)
    assert not missing, f'emitted but not cataloged: {sorted(missing)}'
    dead = set(RULES) - emitted
    assert not dead, f'cataloged but nothing emits them: {sorted(dead)}'


def test_every_rule_prefix_has_a_tier():
    for rule, doc in RULES.items():
        assert rule[:3] in TIERS, f'{rule}: prefix not in TIERS'
        assert doc.tier == TIERS[rule[:3]]
        for field in ('title', 'what', 'why', 'fix', 'severity'):
            assert getattr(doc, field).strip(), f'{rule}.{field} empty'
        assert doc.severity in ('error', 'warning', 'info')
    assert set(RULE_CATALOG) == set(RULES)
    # Every tier with registered rules; CON is the 6th and newest.
    assert {r[:3] for r in RULES} == set(TIERS)


def test_list_rules_covers_every_rule(capsys):
    assert main(['--list-rules']) == 0
    out = capsys.readouterr().out
    for rule in RULES:
        assert rule in out, f'{rule} missing from --list-rules'


def test_explain_renders_every_rule(capsys):
    for rule in RULES:
        text = explain_rule(rule)
        for section in ('What:', 'Why:', 'Fix:', 'severity:', 'tier:'):
            assert section in text, f'{rule}: {section} missing'
    # And through the CLI, all at once.
    assert main(['--explain', ','.join(sorted(RULES))]) == 0
    out = capsys.readouterr().out
    for rule in RULES:
        assert rule in out


def test_reference_page_covers_every_rule_under_its_tier():
    with open(RST) as f:
        rst = f.read()
    for rule, doc in RULES.items():
        assert f'``{rule}``' in rst, f'{rule} missing from lint-rules.rst'
        assert doc.title in rst, (
            f'{rule}: catalog title not on lint-rules.rst — '
            f'regenerate the page to match catalog.py')
    for prefix in TIERS:
        assert re.search(rf'^{prefix} — ', rst, re.M), (
            f'tier section {prefix} missing from lint-rules.rst')
