"""Recompile-hazard pass: bucket dominance + telemetry cross-check."""

from dgmc_tpu.analysis import analyze_buckets, bucket_signature


def _bucket(batch, nodes, edges, count=1):
    return {'batch': batch, 'nodes': nodes, 'edges': edges, 'count': count}


def test_identical_buckets_share_a_signature():
    a = _bucket(8, '32x40', '64x80')
    b = _bucket(8, '32x40', '64x80', count=5)
    assert bucket_signature(a) == bucket_signature(b)


def test_different_padding_changes_the_signature():
    assert (bucket_signature(_bucket(8, '32x40', '64x80'))
            != bucket_signature(_bucket(8, '33x40', '64x80')))


def test_dominated_bucket_flagged_rcp201():
    buckets = [_bucket(8, '32x40', '64x80'),
               _bucket(8, '24x40', '64x80', count=3)]
    findings = analyze_buckets(buckets)
    assert [f.rule for f in findings] == ['RCP201']
    assert 'nodes=24x40' in findings[0].message
    assert 'dominated by' in findings[0].message


def test_incomparable_buckets_are_clean():
    # Neither dominates: one is wider in nodes, the other in edges.
    buckets = [_bucket(8, '48x40', '64x80'),
               _bucket(8, '32x40', '96x80')]
    assert analyze_buckets(buckets) == []


def test_pair_batch_axis_is_not_a_padding_axis():
    """No RCP201 churn across B in {1, 2}: the --pairs-per-step batch
    axis is structural (padding B replicates the whole per-pair cost
    and changes the step's gradient semantics), so same-padding buckets
    that differ only in B are distinct programs by design."""
    buckets = [_bucket(1, '32x40', '64x80'),
               _bucket(2, '32x40', '64x80')]
    assert analyze_buckets(buckets) == []
    # ... and they stay distinct signatures for the RCP202 budget.
    assert (bucket_signature(buckets[0])
            != bucket_signature(buckets[1]))


def test_domination_still_fires_at_equal_pair_batch():
    """The B-axis carve-out must not blind the rule to real padding
    churn: smaller node padding at the SAME B is still dominated."""
    buckets = [_bucket(2, '32x40', '64x80'),
               _bucket(2, '24x40', '64x80'),
               _bucket(1, '24x40', '64x80')]
    findings = analyze_buckets(buckets)
    assert [f.rule for f in findings] == ['RCP201']
    assert 'B=2,nodes=24x40' in findings[0].message


def test_single_bucket_is_clean():
    assert analyze_buckets([_bucket(8, '32x40', '64x80')]) == []


def test_telemetry_crosscheck_fires_rcp202():
    buckets = [_bucket(8, '32x40', '64x80')]
    findings = analyze_buckets(buckets, compile_events=50)
    assert [f.rule for f in findings] == ['RCP202']
    assert '50 compile events' in findings[0].message


def test_telemetry_within_budget_is_clean():
    buckets = [_bucket(8, '32x40', '64x80')]
    assert analyze_buckets(buckets, compile_events=3) == []


def test_obs_dir_roundtrip(tmp_path):
    import json
    from dgmc_tpu.analysis.recompile import load_obs_buckets
    (tmp_path / 'timings.json').write_text(json.dumps({
        'compile': {'events': 4},
        'padding_buckets': [
            {'batch': 8, 'nodes': '32x40', 'edges': '64x80', 'count': 7}],
    }))
    buckets, events = load_obs_buckets(str(tmp_path))
    assert events == 4
    assert buckets[0]['count'] == 7
    assert load_obs_buckets(str(tmp_path / 'missing')) == ([], None)
