"""Source-tier golden tests over the fixtures module + bytecode refusal."""

import os

import pytest

from dgmc_tpu.analysis import lint_source_file
from dgmc_tpu.analysis.source_rules import iter_source_files

FIXTURES = os.path.join(os.path.dirname(__file__), 'fixtures.py')


def _by_rule(findings):
    out = {}
    for f in findings:
        out.setdefault(f.rule, []).append(f)
    return out


@pytest.fixture(scope='module')
def findings():
    return lint_source_file(FIXTURES)


def test_fixture_file_trips_every_source_rule(findings):
    assert sorted(_by_rule(findings)) == ['SRC101', 'SRC102', 'SRC103',
                                          'SRC104']


def test_tracer_leak_on_self(findings):
    (f,) = _by_rule(findings)['SRC101']
    assert '`self.last`' in f.message
    assert '`step`' in f.message


def test_host_sync_float(findings):
    (f,) = _by_rule(findings)['SRC102']
    assert '`float(...)`' in f.message
    assert '`host_sync`' in f.message


def test_jit_in_loop(findings):
    (f,) = _by_rule(findings)['SRC103']
    assert 'inside a loop' in f.message


def test_unhashable_static_default(findings):
    (f,) = _by_rule(findings)['SRC104']
    assert '`cfg`' in f.message
    assert 'list' in f.message


def test_findings_carry_file_line_locations(findings):
    for f in findings:
        path, line = f.where.rsplit(':', 1)
        assert path.endswith('fixtures.py')
        assert int(line) > 0


def test_refuses_pyc(tmp_path):
    pyc = tmp_path / 'mod.pyc'
    pyc.write_bytes(b'\x00\x00\x00\x00')
    with pytest.raises(ValueError, match='refusing to scan bytecode'):
        lint_source_file(str(pyc))


def test_refuses_pycache_paths(tmp_path):
    d = tmp_path / '__pycache__'
    d.mkdir()
    src = d / 'mod.py'
    src.write_text('x = 1\n')
    with pytest.raises(ValueError, match='refusing to scan bytecode'):
        lint_source_file(str(src))


def test_walker_never_descends_into_pycache(tmp_path):
    (tmp_path / 'ok.py').write_text('x = 1\n')
    cache = tmp_path / '__pycache__'
    cache.mkdir()
    (cache / 'stale.py').write_text('x = 1\n')
    (cache / 'stale.pyc').write_bytes(b'\x00')
    found = [os.path.basename(p) for p in iter_source_files(str(tmp_path))]
    assert found == ['ok.py']


def test_unhashable_static_kwonly_and_posonly(tmp_path):
    """static_argnames reaching a KEYWORD-ONLY param's mutable default,
    and static_argnums indexing across positional-only params."""
    p = tmp_path / 'kwonly.py'
    p.write_text(
        'import functools\n'
        'import jax\n\n\n'
        "@functools.partial(jax.jit, static_argnames=('cfg',))\n"
        'def step(x, *, cfg={}):\n'
        '    return x\n\n\n'
        '@functools.partial(jax.jit, static_argnums=(1,))\n'
        'def posonly(x, /, opts=[1]):\n'
        '    return x\n')
    findings = lint_source_file(str(p))
    assert sorted(f.rule for f in findings) == ['SRC104', 'SRC104']
    msgs = ' '.join(f.message for f in findings)
    assert '`cfg`' in msgs and '`opts`' in msgs


def test_clean_file_produces_no_findings(tmp_path):
    p = tmp_path / 'clean.py'
    p.write_text(
        'import jax\n\n'
        '@jax.jit\n'
        'def f(x):\n'
        '    return x * 2.0\n')
    assert lint_source_file(str(p)) == []
