"""The shared post-GSPMD HLO walker: module parsing, collective
schedules (regions included), and the aggregate table obs/cost rides.
"""

import jax
import numpy as np
import pytest

from dgmc_tpu.analysis import hlo_comm

# A hand-written partitioned module exercising every structural feature
# the walker must understand: ENTRY order, a while body/condition pair,
# a conditional with branch computations, async -start/-done pairing,
# channel ids, both replica_groups spellings, and a call target.
MODULE = (
    'HloModule jit_step, entry_computation_layout={()->f32[]}\n'
    '\n'
    '%add.clone (x.1: f32[], y.1: f32[]) -> f32[] {\n'
    '  %x.1 = f32[] parameter(0)\n'
    '  %y.1 = f32[] parameter(1)\n'
    '  ROOT %add.2 = f32[] add(f32[] %x.1, f32[] %y.1)\n'
    '}\n'
    '\n'
    '%branch_a (p0: f32[4]) -> f32[4] {\n'
    '  %p0 = f32[4]{0} parameter(0)\n'
    '  ROOT %ar.a = f32[4]{0} all-reduce(f32[4]{0} %p0),'
    ' channel_id=7, replica_groups={{0,1},{2,3}}, to_apply=%add.clone\n'
    '}\n'
    '\n'
    '%branch_b (p1: f32[4]) -> f32[4] {\n'
    '  ROOT %p1 = f32[4]{0} parameter(0)\n'
    '}\n'
    '\n'
    '%helper (h0: f32[8]) -> f32[8] {\n'
    '  %h0 = f32[8]{0} parameter(0)\n'
    '  ROOT %cp = f32[8]{0} collective-permute(f32[8]{0} %h0),'
    ' channel_id=9, source_target_pairs={{0,1},{1,0}}\n'
    '}\n'
    '\n'
    '%body (carry: (s32[], f32[4,8])) -> (s32[], f32[4,8]) {\n'
    '  %carry = (s32[], f32[4,8]{1,0}) parameter(0)\n'
    '  %gte = f32[4,8]{1,0}'
    ' get-tuple-element((s32[], f32[4,8]{1,0}) %carry), index=1\n'
    '  %ar.body = f32[4,8]{1,0} all-reduce(f32[4,8]{1,0} %gte),'
    ' channel_id=1, replica_groups=[2,2]<=[4], to_apply=%add.clone\n'
    '  %i = s32[] get-tuple-element((s32[], f32[4,8]{1,0}) %carry),'
    ' index=0\n'
    '  ROOT %t = (s32[], f32[4,8]{1,0}) tuple(s32[] %i,'
    ' f32[4,8]{1,0} %ar.body)\n'
    '}\n'
    '\n'
    '%cond (carry.1: (s32[], f32[4,8])) -> pred[] {\n'
    '  %carry.1 = (s32[], f32[4,8]{1,0}) parameter(0)\n'
    '  %i.1 = s32[]'
    ' get-tuple-element((s32[], f32[4,8]{1,0}) %carry.1), index=0\n'
    '  %c10 = s32[] constant(10)\n'
    '  ROOT %lt = pred[] compare(s32[] %i.1, s32[] %c10),'
    ' direction=LT\n'
    '}\n'
    '\n'
    'ENTRY %main_spmd (param: f32[4,8], p2: f32[4], p3: s32[],'
    ' p4: f32[8]) -> f32[] {\n'
    '  %param = f32[4,8]{1,0} parameter(0)\n'
    '  %p2 = f32[4]{0} parameter(1)\n'
    '  %p3 = s32[] parameter(2)\n'
    '  %p4 = f32[8]{0} parameter(3)\n'
    '  %init = (s32[], f32[4,8]{1,0}) tuple(s32[] %p3,'
    ' f32[4,8]{1,0} %param)\n'
    '  %loop = (s32[], f32[4,8]{1,0})'
    ' while((s32[], f32[4,8]{1,0}) %init), condition=%cond,'
    ' body=%body\n'
    '  %cc = f32[4]{0} conditional(s32[] %p3, f32[4]{0} %p2,'
    ' f32[4]{0} %p2), branch_computations={%branch_a, %branch_b}\n'
    '  %called = f32[8]{0} call(f32[8]{0} %p4), to_apply=%helper\n'
    '  %ags = f32[16]{0} all-gather-start(f32[4]{0} %cc),'
    ' channel_id=3, replica_groups={{0,1,2,3}}, dimensions={0}\n'
    '  %agd = f32[16]{0} all-gather-done(f32[16]{0} %ags)\n'
    '  ROOT %out = f32[] constant(0)\n'
    '}\n'
)


def test_parse_module_computations_and_entry():
    mod = hlo_comm.parse_hlo_module(MODULE)
    assert mod.entry == 'main_spmd'
    assert {'add.clone', 'branch_a', 'branch_b', 'helper', 'body',
            'cond', 'main_spmd'} <= set(mod.computations)
    assert [op.opcode for op in mod.computations['body'].ops] == [
        'parameter', 'get-tuple-element', 'all-reduce',
        'get-tuple-element', 'tuple']


def test_collective_schedule_walks_regions_in_program_order():
    sched = hlo_comm.collective_schedule(MODULE)
    # while body's all-reduce, both conditional branches, the called
    # helper's collective-permute, then the async all-gather — once.
    assert [c.kind for c in sched] == [
        'all-reduce', 'all-reduce', 'collective-permute', 'all-gather']
    by_comp = {c.computation: c for c in sched}
    assert by_comp['body'].channel_id == 1
    assert by_comp['body'].replica_groups == '[2,2]<=[4]'
    assert by_comp['body'].nbytes == 4 * 8 * 4
    assert by_comp['branch_a'].replica_groups == '{{0,1},{2,3}}'
    assert by_comp['helper'].kind == 'collective-permute'
    ag = by_comp['main_spmd']
    assert ag.kind == 'all-gather' and ag.channel_id == 3
    assert ag.nbytes == 16 * 4


def test_branch_computations_both_spellings():
    mod = hlo_comm.parse_hlo_module(MODULE)
    (cond_op,) = [op for _, op in mod.iter_ops()
                  if op.opcode == 'conditional']
    assert cond_op.branch_computations() == ['branch_a', 'branch_b']
    legacy = hlo_comm.HloOp(
        result='c', result_type='f32[4]',
        opcode='conditional',
        line='%c = f32[4]{0} conditional(pred[] %p, f32[4]{0} %a, '
             'f32[4]{0} %b), true_computation=%t, false_computation=%f')
    assert legacy.branch_computations() == ['t', 'f']


def test_while_bodies_and_flatten():
    mod = hlo_comm.parse_hlo_module(MODULE)
    [(while_op, body)] = mod.while_bodies()
    assert while_op.opcode == 'while' and body == 'body'
    kinds = [c.kind for c in mod.flatten_collectives(body)]
    assert kinds == ['all-reduce']


def test_operands_and_metadata():
    line = ('%ar = f32[4,8]{1,0} all-reduce(f32[4,8]{1,0} %dot), '
            'channel_id=1, replica_groups={{0,1},{2,3}}, '
            'use_global_device_ids=true, to_apply=%add, '
            'metadata={op_name="jit(f)/jit(main)/psi1/dot_general" '
            'source_file="/x/dgmc_tpu/models/dgmc.py" source_line=42}')
    op = hlo_comm.HloOp(result='ar', result_type='f32[4,8]{1,0}',
                        opcode='all-reduce', line=line)
    assert op.operands() == [('f32', (4, 8), 'dot')]
    assert op.op_name == 'jit(f)/jit(main)/psi1/dot_general'
    assert op.source_loc == 'dgmc_tpu/models/dgmc.py:42'
    assert op.collective_kind == 'all-reduce'
    # to_apply on a collective is the combiner, not a region to walk.
    assert op.called_computations() == []


def test_collective_table_matches_schedule_counts():
    t = hlo_comm.collective_table(MODULE)
    assert t['ops']['all-reduce']['count'] == 2
    assert t['ops']['all-gather']['count'] == 1
    assert t['ops']['collective-permute']['count'] == 1
    assert t['count'] == 4


def test_collective_table_stablehlo_spelling():
    txt = ('%0 = "stablehlo.all_reduce"(%arg0) ... : '
           '(tensor<4x8xf32>) -> tensor<4x8xf32>\n')
    t = hlo_comm.collective_table(txt)
    assert t['ops']['all-reduce'] == {'count': 1, 'bytes': 4 * 8 * 4}


def test_hlo_shape_bytes_ignores_layouts():
    assert hlo_comm.hlo_shape_bytes('f32[128,4]{1,0}') == 128 * 4 * 4
    assert hlo_comm.hlo_shape_bytes('(s32[], bf16[8,8]{1,0})') == \
        4 + 8 * 8 * 2


# A pipelined loop: the collective-permute STARTS inside the while body
# (threaded out through the carry) and its -done lands in ENTRY after
# the loop — the while-boundary split. The pair must count ONCE.
SPLIT_ASYNC = (
    '%body (carry: (s32[], f32[8])) -> (s32[], f32[8]) {\n'
    '  %carry = (s32[], f32[8]{0}) parameter(0)\n'
    '  %v = f32[8]{0} get-tuple-element((s32[], f32[8]{0}) %carry),'
    ' index=1\n'
    '  %cps = f32[8]{0} collective-permute-start(f32[8]{0} %v),'
    ' channel_id=5, source_target_pairs={{0,1},{1,0}}\n'
    '  %i = s32[] get-tuple-element((s32[], f32[8]{0}) %carry),'
    ' index=0\n'
    '  ROOT %t = (s32[], f32[8]{0}) tuple(s32[] %i, f32[8]{0} %cps)\n'
    '}\n'
    '\n'
    '%cond (c: (s32[], f32[8])) -> pred[] {\n'
    '  %c = (s32[], f32[8]{0}) parameter(0)\n'
    '  %i.1 = s32[] get-tuple-element((s32[], f32[8]{0}) %c), index=0\n'
    '  %lim = s32[] constant(4)\n'
    '  ROOT %lt = pred[] compare(s32[] %i.1, s32[] %lim),'
    ' direction=LT\n'
    '}\n'
    '\n'
    'ENTRY %main (x: f32[8], i0: s32[]) -> f32[8] {\n'
    '  %x = f32[8]{0} parameter(0)\n'
    '  %i0 = s32[] parameter(1)\n'
    '  %init = (s32[], f32[8]{0}) tuple(s32[] %i0, f32[8]{0} %x)\n'
    '  %loop = (s32[], f32[8]{0}) while((s32[], f32[8]{0}) %init),'
    ' condition=%cond, body=%body\n'
    '  %pending = f32[8]{0}'
    ' get-tuple-element((s32[], f32[8]{0}) %loop), index=1\n'
    '  ROOT %cpd = f32[8]{0}'
    ' collective-permute-done(f32[8]{0} %pending), channel_id=5\n'
    '}\n'
)


def test_split_async_pair_counts_once():
    """-start in the while body, -done in ENTRY: one collective, not
    two (the done consumed a loop-carried start), and the schedule
    walk agrees with the table."""
    t = hlo_comm.collective_table(SPLIT_ASYNC)
    assert t['ops'] == {'collective-permute': {'count': 1,
                                              'bytes': 8 * 4}}
    sched = hlo_comm.collective_schedule(SPLIT_ASYNC)
    assert [c.kind for c in sched] == ['collective-permute']
    assert sched[0].computation == 'body'


def test_orphan_done_stands_in_for_its_pair():
    """A -done whose -start is entirely absent (truncated dump / start
    hidden in an unparsed region) still counts its pair once — never
    zero."""
    fragment = (
        'ENTRY %main (p: f32[16]) -> f32[16] {\n'
        '  %p = f32[16]{0} parameter(0)\n'
        '  ROOT %agd = f32[16]{0} all-gather-done(f32[16]{0} %p),'
        ' channel_id=9\n'
        '}\n'
    )
    t = hlo_comm.collective_table(fragment)
    assert t['ops'] == {'all-gather': {'count': 1, 'bytes': 16 * 4}}
    sched = hlo_comm.collective_schedule(fragment)
    assert [c.kind for c in sched] == ['all-gather']


def test_same_computation_pair_still_counts_once():
    """Control: the in-computation pair (the MODULE fixture's all-gather
    start/done) is unchanged — counted at its start, done invisible."""
    mod = hlo_comm.parse_hlo_module(MODULE)
    assert mod.orphan_done_ids() == frozenset()
    assert hlo_comm.collective_table(MODULE)['count'] == 4


@pytest.mark.skipif(len(jax.devices()) < 4, reason='needs 4 devices')
def test_real_partitioned_program_schedule():
    """A genuinely GSPMD-partitioned reduction must expose its
    all-reduce through the structured walker (not fixture text)."""
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    mesh = Mesh(mesh_utils.create_device_mesh(
        (2, 2), devices=np.asarray(jax.devices()[:4])),
        ('data', 'model'))

    def f(x, w):
        return (x @ w).sum()

    jf = jax.jit(f, in_shardings=(
        NamedSharding(mesh, P('data', 'model')),
        NamedSharding(mesh, P('model', None))))
    txt = jf.lower(np.ones((8, 8), np.float32),
                   np.ones((8, 4), np.float32)).compile().as_text()
    sched = hlo_comm.collective_schedule(txt)
    assert any(c.kind == 'all-reduce' for c in sched)
    assert all(c.channel_id is not None for c in sched)
    # The aggregate table and the schedule must agree on the count.
    assert hlo_comm.collective_table(txt)['count'] == len(sched)
