"""Pallas kernels execute inside shard_map manual mode (VERDICT r3 #4).

The kernels are shard-local computations; round 3 silenced them under any
manual-mode program (``jax.typeof(x).vma`` gates), forfeiting kernel
speed on every sharded path. Now they declare their varying-manual-axes
type (``vma`` on ``out_shape``; see ``ops/pallas/dispatch.vma_union``)
and run per shard. On this CPU test platform the kernels run in
interpret mode under ``check_vma=False`` (interpret mode traces the
kernel body through the vma type system, where internal constants are
unvarying by construction); on a real TPU the same calls compile — the
single-chip mesh measurement is in bench.py's ``topk_ms['shard_map']``.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from dgmc_tpu.ops.pallas.topk import pallas_topk
from dgmc_tpu.ops.topk import dense_topk
from dgmc_tpu.parallel.compat import HAS_NATIVE_SHARD_MAP, shard_map
from dgmc_tpu.parallel.mesh import make_mesh

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason='needs 8 devices')


def test_pallas_topk_rows_under_shard_map():
    mesh = make_mesh(data=1, model=8)
    r = np.random.RandomState(0)
    h_s = jnp.asarray(r.randn(2, 64, 16).astype(np.float32))
    h_t = jnp.asarray(r.randn(2, 96, 16).astype(np.float32))
    t_mask = jnp.asarray(r.rand(2, 96) < 0.9)
    interp = jax.default_backend() != 'tpu'

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(None, 'model', None), P(), P()),
        out_specs=P(None, 'model', None), check_vma=False)
    def rows(hs, ht, tm):
        return pallas_topk(hs, ht, 8, t_mask=tm, interpret=interp)

    got = rows(h_s, h_t, t_mask)
    want = dense_topk(h_s, h_t, 8, t_mask=t_mask)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.skipif(not HAS_NATIVE_SHARD_MAP,
                    reason='pre-vma JAX: shard_map has no pallas_call '
                           'replication rule; check_rep cannot pass')
def test_pallas_topk_vma_declared_under_check_vma():
    """With check_vma ON (the default), the kernel's declared vma makes
    the shard_map typecheck pass on TPU; on CPU the interpret-mode body
    itself is traced under vma rules, so only the abstract-eval path can
    be exercised — assert the out_shape plumbing at least typechecks via
    eval_shape (no kernel execution)."""
    mesh = make_mesh(data=1, model=8)
    r = np.random.RandomState(1)
    h_s = jnp.asarray(r.randn(1, 64, 16).astype(np.float32))
    h_t = jnp.asarray(r.randn(1, 96, 16).astype(np.float32))

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(None, 'model', None), P()),
        out_specs=P(None, 'model', None))
    def rows(hs, ht):
        return pallas_topk(hs, ht, 8)

    out = jax.eval_shape(rows, h_s, h_t)
    assert out.shape == (1, 64, 8)
