"""Ring-rotated target shards in the sharded candidate search.

``corr_sharded_topk(ring=True)`` shards the target set over the row
mesh axis and rotates the shards device-to-device, issuing each
boundary ``collective-permute`` a rotation ahead of the compute that
consumes it. These tests pin the three contracts the rewrite rides on:
bit-identity with the dense reference (ties, ragged targets, masks,
chunk streaming), AD opacity (the search stays gradient-transparent
like every other search path), and the pipeline structure itself (the
permute lives INSIDE the rotation loop body, where the trip-amplified
schedule model weights it).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dgmc_tpu.ops.topk import dense_topk

pytestmark = pytest.mark.skipif(len(jax.devices()) < 4,
                                reason='needs 4 devices')


def _sharding():
    from dgmc_tpu.parallel import make_mesh
    mesh = make_mesh(data=4, model=1, devices=jax.devices()[:4])
    return NamedSharding(mesh, P(None, 'data'))


def test_ring_matches_dense_ties_ragged_masked():
    """Ragged target counts (padding), duplicated target rows (value
    ties across SHARD boundaries — the case the index-ordered merge
    exists for), random masks, with and without chunk streaming."""
    from dgmc_tpu.parallel.topk import corr_sharded_topk
    rng = np.random.RandomState(0)
    sh = _sharding()
    for n_t, k, chunk in [(29, 4, None), (32, 5, 8), (24, 6, 4)]:
        base = rng.randn(1, n_t, 8).astype(np.float32)
        base[0, n_t // 2:] = base[0, :n_t - n_t // 2]
        h_s = jnp.asarray(rng.randn(1, 16, 8).astype(np.float32))
        h_t = jnp.asarray(base)
        tm = jnp.asarray(rng.rand(1, n_t) > 0.3)
        ref = dense_topk(h_s, h_t, k, t_mask=tm)
        got = corr_sharded_topk(sh, h_s, h_t, k, tm, block=8,
                                chunk=chunk, ring=True)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_ring_all_equal_scores_tie_order():
    """All-equal scores: the merge must reproduce lax.top_k's
    lowest-global-index order even though shards arrive rotated."""
    from dgmc_tpu.parallel.topk import corr_sharded_topk
    sh = _sharding()
    h_s = jnp.ones((1, 16, 4))
    h_t = jnp.ones((1, 32, 4))
    got = corr_sharded_topk(sh, h_s, h_t, 5, None, block=8, ring=True)
    np.testing.assert_array_equal(
        np.asarray(got), np.tile(np.arange(5), (1, 16, 1)))


def test_ring_is_ad_opaque():
    """value_and_grad through a ring search neither fails nor leaks
    residuals: gradients flow through the downstream gather only."""
    from dgmc_tpu.parallel.topk import corr_sharded_topk
    sh = _sharding()
    rng = np.random.RandomState(2)
    h_s = jnp.asarray(rng.randn(1, 16, 8).astype(np.float32))
    h_t = jnp.asarray(rng.randn(1, 24, 8).astype(np.float32))

    def loss(h_s, h_t):
        idx = corr_sharded_topk(sh, h_s, h_t, 4, None, block=8,
                                chunk=8, ring=True)
        g = jnp.take_along_axis(h_t, idx.reshape(1, -1, 1), axis=1)
        return g.sum() + h_s.sum()

    v, grads = jax.value_and_grad(loss, argnums=(0, 1))(h_s, h_t)
    assert np.isfinite(float(v))
    assert all(np.all(np.isfinite(np.asarray(g))) for g in grads)


def test_ring_falls_back_when_k_exceeds_shard():
    """k wider than one target shard cannot ring (a shard must hold a
    full candidate set); the replicated path runs, same results."""
    from dgmc_tpu.parallel.topk import corr_sharded_topk
    sh = _sharding()
    rng = np.random.RandomState(3)
    h_s = jnp.asarray(rng.randn(1, 16, 8).astype(np.float32))
    h_t = jnp.asarray(rng.randn(1, 24, 8).astype(np.float32))
    got = corr_sharded_topk(sh, h_s, h_t, 8, None, block=8, ring=True)
    np.testing.assert_array_equal(np.asarray(got),
                                  np.asarray(dense_topk(h_s, h_t, 8)))


def test_ring_permute_lives_in_loop_body():
    """The pipeline structure, pinned on the compiled program: the
    boundary collective-permute sits inside a while body (so the
    trip-amplified schedule model weights it once per rotation), and
    its source_target_pairs are a forward rotation — the SHD303-exempt
    shape, not a bounce."""
    from dgmc_tpu.analysis.hlo_comm import parse_hlo_module
    from dgmc_tpu.parallel.topk import corr_sharded_topk
    sh = _sharding()
    rng = np.random.RandomState(4)
    h_s = jnp.asarray(rng.randn(1, 16, 8).astype(np.float32))
    h_t = jnp.asarray(rng.randn(1, 32, 8).astype(np.float32))

    fn = jax.jit(lambda a, b: corr_sharded_topk(sh, a, b, 4, None,
                                                block=8, chunk=8,
                                                ring=True))
    module = parse_hlo_module(fn.lower(h_s, h_t).compile().as_text())
    bodies = {b for _, b in module.while_bodies()}
    in_loop = [c for b in bodies for c in module.flatten_collectives(b)
               if c.kind == 'collective-permute']
    assert in_loop, 'ring permute not in any loop body'
    assert any('source_target_pairs={{0,1},{1,2},{2,3},{3,0}}' in c.line
               for c in in_loop), [c.line[:120] for c in in_loop]
