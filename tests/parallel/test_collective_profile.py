"""Pin the COLLECTIVE PROFILE of the corr-sharded sparse train step.

The correctness tests prove the row-sharded DBP15K-shaped step computes the
same numbers as the unsharded one — but an accidental GSPMD regression that
all-gathers the row-sharded correspondence state (``S_hat``/``S_idx``,
``[B, N_s, ...]``) back to every device would pass all of them and only
show up as ICI traffic and replicated memory on real hardware
(VERDICT r4 weakness 4). This test compiles a structure-preserving scaled
DBP15K step (sparse top-k + negatives/GT + blocked adjacency + row-sharded
correspondence over an 8-way model axis) and asserts over the optimized
HLO that:

1. no ``all-gather`` exists at all — the design needs none: rows are
   independent in the candidate search, and the only cross-row coupling is
   the ``r_t = S^T r_s`` projection, which is an all-reduce of the
   *target*-sized partial sums, never a gather of row-sharded state;
2. no collective result carries the full source-row axis ``N_s`` — sharded
   operands stay sharded through the whole step;
3. the inherent projection all-reduce (``[B, N_t, R_in]``) IS present —
   so the test fails loudly if the sharding silently degrades to full
   replication (where no such collective would remain);
4. gradients are reduced a bounded number of times (once per gradient
   group, not once per consensus iteration).
"""

import re

import jax
import numpy as np
import pytest

from dgmc_tpu.models import DGMC, RelCNN
from dgmc_tpu.ops import GraphBatch
from dgmc_tpu.ops.blocked import attach_blocks
from dgmc_tpu.parallel import (corr_sharding, make_mesh,
                               make_sharded_train_step, replicate)
from dgmc_tpu.train import create_train_state
from dgmc_tpu.utils.data import PairBatch

N_S, N_T = 512, 640
R_IN = 8


def _side(n, e, dim, rng):
    return attach_blocks(GraphBatch(
        x=rng.randn(1, n, dim).astype(np.float32),
        senders=rng.randint(0, n, (1, e)).astype(np.int32),
        receivers=rng.randint(0, n, (1, e)).astype(np.int32),
        node_mask=np.ones((1, n), bool),
        edge_mask=np.ones((1, e), bool),
        edge_attr=None), min_nodes=256)


@pytest.fixture(scope='module')
def hlo_text():
    rng = np.random.RandomState(0)
    s, t = _side(N_S, 2000, 32, rng), _side(N_T, 2500, 32, rng)
    y = np.full((1, N_S), -1, np.int32)
    y[0, :150] = rng.permutation(N_T)[:150]
    batch = PairBatch(s=s, t=t, y=y, y_mask=y >= 0)
    mesh = make_mesh(data=1, model=8)
    psi_1 = RelCNN(32, 32, num_layers=2, dropout=0.5)
    psi_2 = RelCNN(R_IN, R_IN, num_layers=2)
    model = DGMC(psi_1, psi_2, num_steps=2, k=4,
                 corr_sharding=corr_sharding(mesh))
    base = DGMC(psi_1, psi_2, num_steps=2, k=4)
    tiny = PairBatch(s=_side(32, 64, 32, rng), t=_side(32, 64, 32, rng),
                     y=np.zeros((1, 32), np.int32),
                     y_mask=np.ones((1, 32), bool))
    state = create_train_state(base, jax.random.key(0), tiny,
                               learning_rate=1e-3)
    step = make_sharded_train_step(model, mesh, batch_axis=None)
    # Module-scoped: lowers BEFORE the conftest's function-scoped
    # autouse fixture, so the RNG pin must wrap this lowering itself
    # (see pinned_partitionable_threefry for why the pin exists).
    from tests.parallel.conftest import pinned_partitionable_threefry
    with pinned_partitionable_threefry():
        return step.lower(replicate(state, mesh), replicate(batch, mesh),
                          jax.random.key(1)).compile().as_text()


def _collectives(txt):
    """(kind, result_shape_dims) for every collective in the HLO text."""
    out = []
    for line in txt.splitlines():
        m = re.search(r'(all-gather|all-reduce|all-to-all|reduce-scatter|'
                      r'collective-permute)\(', line)
        if not m:
            continue
        shape = re.match(r'\s*%?[\w\.\-]+ = (\S+)', line)
        dims = [int(d) for d in
                re.findall(r'\[([\d,]*)\]', shape.group(1) if shape else '')
                for d in d.split(',') if d]
        out.append((m.group(1), dims, line.strip()[:120]))
    return out


def test_no_all_gather_anywhere(hlo_text):
    bad = [c for c in _collectives(hlo_text) if c[0] == 'all-gather']
    assert not bad, (
        'the corr-sharded sparse step needs NO all-gather (rows are '
        f'independent; the projection is an all-reduce): {bad}')


def test_row_sharded_state_never_rides_a_collective(hlo_text):
    bad = [c for c in _collectives(hlo_text) if N_S in c[1]]
    assert not bad, (
        f'collective carries the full N_s={N_S} row axis — row-sharded '
        f'correspondence state must stay sharded: {bad}')


def test_projection_all_reduce_present(hlo_text):
    """The r_t = S^T r_s merge is the design's one inherent collective; its
    absence means the program silently replicated instead of sharding."""
    hits = [c for c in _collectives(hlo_text)
            if c[0] == 'all-reduce' and c[1][:3] == [1, N_T, R_IN]]
    assert hits, 'expected an all-reduce of the [B, N_t, R_in] projection'


def test_grad_reduction_bounded(hlo_text):
    from dgmc_tpu.parallel.compat import HAS_NATIVE_SHARD_MAP
    n = sum(1 for c in _collectives(hlo_text) if c[0] == 'all-reduce')
    # 2 consensus iterations: 2-3 projection reduces + a handful of grad
    # group reduces. A regression into per-iteration re-reduction of
    # gradients or re-gathered state would blow well past this. Pre-0.5
    # GSPMD emits one all-reduce per gradient LEAF (no combiner pass on
    # this path — ~50 for this model) where modern XLA merges them per
    # group; the bound scales accordingly so the per-iteration blowup
    # (O(num_steps * leaves), >100 here) is still caught.
    limit = 20 if HAS_NATIVE_SHARD_MAP else 64
    assert n <= limit, (f'{n} all-reduces (limit {limit}) — grads should '
                        f'reduce once per group')
