"""Partition-rule layer (``dgmc_tpu/parallel/rules.py``): regex →
PartitionSpec matching semantics, the GuardedTrainState round-trip
(params AND optimizer state AND guard counters typed by one rule list),
and the streamed-S execution path pinned numerically against the
unsharded reference at the ``test_dense_sparse_equivalence``
tolerances."""

import jax
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dgmc_tpu.parallel import (PartitionRules, make_mesh,
                               make_sharded_eval_step,
                               make_sharded_train_step,
                               match_partition_rules, replicated_rules,
                               streamed_rules, tree_shardings)
from dgmc_tpu.parallel.rules import leaf_path_str
from dgmc_tpu.train import create_train_state, make_eval_step, \
    make_train_step, with_guard_counters

from tests.train.test_steps import tiny_loader, tiny_model


# ---------------------------------------------------------------------------
# Rule matcher
# ---------------------------------------------------------------------------


def test_first_match_wins():
    tree = {'params': {'psi_1': {'kernel': np.ones((4, 8))},
                       'psi_2': {'kernel': np.ones((4, 8))}}}
    specs = match_partition_rules(
        ((r'psi_1/kernel', P('data')),
         (r'kernel', P('model')),   # would also match psi_1's — must lose
         (r'.*', P())), tree)
    assert specs['params']['psi_1']['kernel'] == P('data')
    assert specs['params']['psi_2']['kernel'] == P('model')


def test_unmatched_leaf_raises_with_path():
    tree = {'params': {'deep': {'odd_name': np.ones((4, 8))}}}
    with pytest.raises(ValueError, match=r'params/deep/odd_name'):
        match_partition_rules(((r'kernel', P()),), tree)


def test_scalars_never_partitioned():
    """Rank-0 / single-element leaves get P() without consulting rules —
    even rules that would otherwise shard them."""
    tree = {'count': np.int32(3), 'one': np.ones((1,)),
            'vec': np.ones((8,))}
    specs = match_partition_rules(((r'.*', P('data')),), tree)
    assert specs['count'] == P()
    assert specs['one'] == P()
    assert specs['vec'] == P('data')


# ~15s end-to-end guarded-state train/restore; the guard semantics
# themselves stay tier-1 in tests/resilience/test_guard_step.py, and
# the rule-typing contract in the lighter tests above.
@pytest.mark.slow
def test_guarded_train_state_round_trip():
    """One rule list types the ENTIRE GuardedTrainState pytree: the spec
    tree has the state's exact structure, optimizer moments follow their
    parameters' rule, and every counter (step, adam count, guard
    ledgers) stays replicated scalar."""
    model = tiny_model(k=4)
    batch = next(iter(tiny_loader(batch_size=2)))
    state = with_guard_counters(
        create_train_state(model, jax.random.key(0), batch,
                           tx=optax.adam(1e-3)))
    # mlp_hidden_kernel is [R_out, R_out] = [8, 8] — the one weight in
    # the tiny model whose trailing axis tiles an 8-way mesh axis.
    rules = ((r'hidden_kernel$', P(None, 'model')), (r'.*', P()))
    specs = match_partition_rules(rules, state)

    # Same treedef — the spec tree types every leaf of the state.
    assert (jax.tree_util.tree_structure(jax.tree.map(lambda x: 0, state))
            == jax.tree_util.tree_structure(
                jax.tree.map(lambda s: 0, specs,
                             is_leaf=lambda x: isinstance(x, P))))

    flat, _ = jax.tree_util.tree_flatten_with_path(
        specs, is_leaf=lambda x: isinstance(x, P))
    by_path = {leaf_path_str(p): s for p, s in flat}
    kernels = [n for n in by_path if n.endswith('hidden_kernel')]
    assert kernels, by_path
    # Optimizer mu/nu moments carry their parameter's rule.
    assert any(n.startswith('opt_state') for n in kernels)
    for n in kernels:
        assert by_path[n] == P(None, 'model'), (n, by_path[n])
    for counter in ('step', 'skip_count', 'consec_bad'):
        assert by_path[counter] == P(), (counter, by_path[counter])
    assert by_path['opt_state/0/count'] == P()

    # Placement round-trip on a real mesh: every leaf lands with its
    # matched sharding and values survive bit-exactly.
    mesh = make_mesh(data=1, model=8)
    cfg = PartitionRules(state=rules)
    placed, _ = cfg.place(state, batch, mesh)
    shardings = tree_shardings(rules, state, mesh)
    for (pth, leaf), sh in zip(
            jax.tree_util.tree_flatten_with_path(placed)[0],
            jax.tree.leaves(shardings,
                            is_leaf=lambda x: isinstance(x, NamedSharding))):
        if hasattr(leaf, 'sharding'):
            assert leaf.sharding.is_equivalent_to(sh, leaf.ndim), pth
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(placed)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_replicated_rules_match_legacy_behavior():
    rules = replicated_rules()
    assert rules.batch == P('data')
    assert rules.activation_spec('corr') is None
    st = streamed_rules(stream_chunk=64)
    assert st.activation_spec('corr') == P(None, 'data')
    # 'topk' falls back to 'corr' when not separately ruled.
    assert PartitionRules(
        activations={'corr': P(None, 'data')}).activation_spec('topk') \
        == P(None, 'data')


# ---------------------------------------------------------------------------
# Streamed-S execution, pinned against the unsharded reference
# ---------------------------------------------------------------------------


def test_stream_chunk_matches_unstreamed_forward():
    """Source-chunk streaming is a pure scheduling change: S_0/S_L must
    match the unstreamed sparse forward at the dense≡sparse equivalence
    tolerances (the shortlist is bit-identical, so the downstream math
    is too)."""
    base = tiny_model(k=4)
    streamed = base.clone(stream_chunk=5)  # ragged vs N_s=12: pads
    batch = next(iter(tiny_loader(batch_size=2)))
    rngs = {'noise': jax.random.PRNGKey(7),
            'negatives': jax.random.PRNGKey(8)}
    variables = base.init({'params': jax.random.PRNGKey(0), **rngs},
                          batch.s, batch.t)
    (S0_a, SL_a) = base.apply(variables, batch.s, batch.t, rngs=rngs)
    (S0_b, SL_b) = streamed.apply(variables, batch.s, batch.t, rngs=rngs)
    np.testing.assert_array_equal(np.asarray(S0_a.idx),
                                  np.asarray(S0_b.idx))
    np.testing.assert_allclose(S0_a.val, S0_b.val, atol=1e-6)
    np.testing.assert_allclose(SL_a.val, SL_b.val, atol=1e-6)


def test_streamed_dense_rejected():
    with pytest.raises(ValueError, match='stream_chunk'):
        tiny_model(k=-1).clone(stream_chunk=8).apply(
            {}, None, None)  # raises before touching args


@pytest.mark.slow
def test_streamed_rules_train_eval_match_reference():
    """The full rules-driven path (S row-sharded over ``data``, streamed
    shortlisting, rule-typed state in/out shardings) against the
    unsharded step on a small pair — the million-entity layout's
    correctness pin (tolerances follow the existing sharded tests: the
    partitioned program may re-order f32 reductions)."""
    mesh = make_mesh(data=8, model=1)
    base = tiny_model(k=4)
    rules = streamed_rules(stream_chunk=4)
    loader = tiny_loader(batch_size=1)
    batch = next(iter(loader))
    state = create_train_state(base, jax.random.key(0), batch,
                               tx=optax.sgd(1e-2))
    key = jax.random.key(2)

    ref_step = make_train_step(base, jit=False)
    sh_step = make_sharded_train_step(base, mesh, rules=rules, state=state)

    _, ref_out = ref_step(state, batch, key)
    state_sh, batch_sh = rules.place(jax.tree.map(np.asarray, state),
                                     batch, mesh)
    state_sh, sh_out = sh_step(state_sh, batch_sh, key)
    assert float(sh_out['loss']) == pytest.approx(float(ref_out['loss']),
                                                  rel=1e-4)
    assert float(sh_out['acc']) == pytest.approx(float(ref_out['acc']),
                                                 abs=1e-6)

    ref_eval = make_eval_step(base, hits_ks=(1,))
    sh_eval = make_sharded_eval_step(base, mesh, hits_ks=(1,),
                                     rules=rules, state=state)
    ev_ref = ref_eval(state, batch, key)
    ev_sh = sh_eval(rules.place(jax.tree.map(np.asarray, state),
                                batch, mesh)[0], batch_sh, key)
    assert float(ev_sh['correct']) == pytest.approx(
        float(ev_ref['correct']), abs=1e-6)
    assert float(ev_sh['count']) == float(ev_ref['count'])
