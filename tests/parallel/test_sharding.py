"""Sharded training over the virtual 8-device mesh: data-parallel batch
sharding and correspondence (activation) sharding must reproduce the
single-device step's numbers."""

import jax
import numpy as np
import pytest

from dgmc_tpu.models import DGMC
from dgmc_tpu.parallel import (corr_sharding, make_mesh,
                               make_sharded_train_step,
                               replicate, shard_batch)
from dgmc_tpu.train import create_train_state, make_train_step

from tests.train.test_steps import tiny_loader, tiny_model


def test_dp_matches_single_device():
    mesh = make_mesh(data=4, model=2)
    model = tiny_model(k=-1)
    loader = tiny_loader(batch_size=4)
    batch = next(iter(loader))
    # SGD: the update is linear in the gradient, so single-device and
    # sharded runs stay in numerical lockstep (Adam's eps-divide would
    # amplify reduction-order noise on near-zero gradients).
    import optax
    state = create_train_state(model, jax.random.key(0), batch,
                               tx=optax.sgd(1e-2))
    state_sh = replicate(jax.tree.map(np.asarray, state), mesh)

    key = jax.random.key(1)
    ref_step = make_train_step(model, loss_on_s0=True)
    sh_step = make_sharded_train_step(model, mesh, loss_on_s0=True)

    state, ref_out = ref_step(state, batch, key)
    state_sh, sh_out = sh_step(state_sh, shard_batch(batch, mesh), key)

    # rel=5e-4: the partitioned forward legitimately re-orders f32
    # reductions (GSPMD may split unbatched internal ops over the idle
    # 'model' axis too), which moves this tiny-batch loss by ~3e-4
    # relative — deterministic, reproducible standalone, and within one
    # SGD step's noise floor. Lockstep is pinned where it is exact-able:
    # the parameter comparison below keeps its tight tolerances.
    assert float(sh_out['loss']) == pytest.approx(float(ref_out['loss']),
                                                  rel=5e-4)
    assert float(sh_out['acc']) == pytest.approx(float(ref_out['acc']),
                                                 abs=1e-6)
    # Parameters stay in lockstep after the update. Tolerances sit just
    # above the measured GSPMD noise floor on the 8-virtual-device CPU
    # backend (max |Δ| ~2.5e-4 after one lr=1e-2 step, reproducible with
    # donation and caching both off): the partitioned program reassociates
    # f32 reductions and the consensus loop's softmax feedback amplifies
    # that over num_steps iterations. A genuine DP bug (per-shard
    # statistics, missing grad psum) diverges by orders of magnitude more.
    for a, b in zip(jax.tree.leaves(state.params),
                    jax.tree.leaves(state_sh.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3,
                                   atol=5e-4)


# The dense (-1) arm re-runs the whole sharded-vs-unconstrained parity
# at the largest workload (~28s); the top-k arm keeps the constraint
# machinery covered in tier-1.
@pytest.mark.parametrize('k', [pytest.param(-1, marks=pytest.mark.slow),
                               4])
def test_corr_sharding_matches_unconstrained(k):
    """Row-sharding the correspondence state over the model axis is a pure
    layout annotation — results must not change."""
    mesh = make_mesh(data=1, model=8)
    base = tiny_model(k=k)
    # N_s = 12 is not divisible by 8; GSPMD pads internally — still valid.
    sharded = DGMC(base.psi_1, base.psi_2, num_steps=base.num_steps, k=k,
                   corr_sharding=corr_sharding(mesh))

    loader = tiny_loader(batch_size=2)
    batch = next(iter(loader))
    state = create_train_state(base, jax.random.key(0), batch)
    key = jax.random.key(2)

    ref_step = make_train_step(base, jit=False)
    sh_step = make_sharded_train_step(sharded, mesh, batch_axis=None)

    _, ref_out = ref_step(state, batch, key)
    state_sh = replicate(jax.tree.map(np.asarray, state), mesh)
    _, sh_out = sh_step(state_sh, replicate(batch, mesh), key)
    assert float(sh_out['loss']) == pytest.approx(float(ref_out['loss']),
                                                  rel=1e-4)


def test_gspmd_safe_disables_auto_kernels_at_trace_time():
    """jax.jit(in_shardings=...) partitioning is invisible to
    jax.typeof(...).vma, so the sharded step builders must silence every
    auto-dispatched Pallas gate while tracing (a pallas_call inside a
    GSPMD-partitioned program crashes or silently replicates)."""
    import jax.numpy as jnp

    from dgmc_tpu.ops.pallas.dispatch import fused_kernels_allowed
    from dgmc_tpu.parallel.sharding import _gspmd_safe

    seen = []

    def probe(x):
        seen.append(fused_kernels_allowed())
        return x * 2

    mesh = make_mesh(data=4, model=2)
    jax.jit(_gspmd_safe(probe, mesh))(jnp.ones(8))
    assert seen == [False]

    # A single-device mesh never partitions: kernels stay enabled.
    seen.clear()
    mesh1 = make_mesh(data=1, model=1, devices=jax.devices()[:1])
    jax.jit(_gspmd_safe(probe, mesh1))(jnp.ones(8))
    assert seen == [True]


@pytest.mark.slow
def test_corr_sharding_embedded_kernel_topk_path():
    """When (B, N_s) tile the corr mesh evenly, the sparse candidate
    search runs as shard_map manual code EMBEDDED in the GSPMD program
    (parallel/topk.corr_sharded_topk) — results must match the
    unsharded step exactly (the embedding is bit-identical by design)."""
    from dgmc_tpu.models import SplineCNN
    from dgmc_tpu.data import (Cartesian, Compose, Constant, KNNGraph,
                               RandomGraphPairs)
    from dgmc_tpu.utils import PairLoader
    from dgmc_tpu.parallel.topk import corr_sharded_topk

    mesh = make_mesh(data=2, model=4)
    transform = Compose([Constant(), KNNGraph(k=4), Cartesian()])
    ds = RandomGraphPairs(min_inliers=8, max_inliers=12, min_outliers=0,
                          max_outliers=2, transform=transform, length=4,
                          seed=3)
    # B=2 tiles data=2; N_s=16 tiles model=4 -> the embedding is LIVE
    # (corr_sharded_topk returns non-None), unlike the ragged test above.
    loader = PairLoader(ds, 2, shuffle=False, num_nodes=16, num_edges=64)
    batch = next(iter(loader))
    sh = corr_sharding(mesh)
    assert corr_sharded_topk(
        sh, jax.numpy.zeros((2, 16, 8)), jax.numpy.zeros((2, 16, 8)),
        4, None) is not None

    psi_1 = SplineCNN(1, 16, dim=2, num_layers=2, cat=False, lin=True)
    psi_2 = SplineCNN(8, 8, dim=2, num_layers=2, cat=True, lin=True)
    base = DGMC(psi_1, psi_2, num_steps=2, k=4)
    sharded = DGMC(psi_1, psi_2, num_steps=2, k=4, corr_sharding=sh)

    state = create_train_state(base, jax.random.key(0), batch)
    key = jax.random.key(2)
    ref_step = make_train_step(base, jit=False)
    sh_step = make_sharded_train_step(sharded, mesh)

    _, ref_out = ref_step(state, batch, key)
    state_sh = replicate(jax.tree.map(np.asarray, state), mesh)
    _, sh_out = sh_step(state_sh, shard_batch(batch, mesh), key)
    assert float(sh_out['loss']) == pytest.approx(float(ref_out['loss']),
                                                  rel=1e-4)
    assert float(sh_out['acc']) == pytest.approx(float(ref_out['acc']),
                                                 abs=1e-6)
