"""Multi-host initialization glue: single-process no-op semantics,
idempotence, and delegation of cluster detection to JAX (a real pod cannot
run here; the contract is that scripts call initialize_distributed
unconditionally)."""

import jax
import pytest

from dgmc_tpu.parallel import distributed, initialize_distributed
from dgmc_tpu.parallel import is_coordinator


@pytest.fixture(autouse=True)
def fresh(monkeypatch):
    monkeypatch.setattr(distributed, '_initialized', False)
    monkeypatch.setattr(distributed, '_already_initialized', lambda: False)


def test_single_process_noop_and_idempotent(monkeypatch):
    def detect_fail(**kw):  # what bare initialize() does with no cluster
        raise ValueError('coordinator_address should be defined.')

    monkeypatch.setattr(jax.distributed, 'initialize', detect_fail)
    assert initialize_distributed() == 1
    assert initialize_distributed() == 1  # idempotent, no second attempt
    assert is_coordinator()


def test_cluster_detection_is_delegated(monkeypatch):
    """With no args, bare jax.distributed.initialize() runs — JAX's own
    cluster auto-detection (SLURM/MPI/TPU pods) decides."""
    called = []
    monkeypatch.setattr(jax.distributed, 'initialize',
                        lambda **kw: called.append(kw))
    initialize_distributed()
    assert called == [{}]


def test_coordinator_args_are_forwarded(monkeypatch):
    calls = {}

    def fake_init(coordinator_address=None, num_processes=None,
                  process_id=None):
        calls.update(addr=coordinator_address, n=num_processes,
                     pid=process_id)

    monkeypatch.setattr(jax.distributed, 'initialize', fake_init)
    initialize_distributed('host:1234', 4, 2)
    assert calls == {'addr': 'host:1234', 'n': 4, 'pid': 2}


def test_external_initialization_is_respected(monkeypatch):
    """A launcher that already brought the runtime up must not trigger a
    second initialize (which would raise)."""
    monkeypatch.setattr(distributed, '_already_initialized', lambda: True)

    def boom(**kw):
        raise AssertionError('re-initialized an initialized runtime')

    monkeypatch.setattr(jax.distributed, 'initialize', boom)
    assert initialize_distributed() == 1
