"""Multi-host initialization glue: single-process no-op semantics,
idempotence, and delegation of cluster detection to JAX (a real pod cannot
run here; the contract is that scripts call initialize_distributed
unconditionally, even late)."""

import jax
import pytest

from dgmc_tpu.parallel import distributed, initialize_distributed
from dgmc_tpu.parallel import is_coordinator


@pytest.fixture(autouse=True)
def fresh(monkeypatch):
    monkeypatch.setattr(distributed, '_initialized', False)


def test_real_environment_noop():
    """No mocks: in this suite the XLA backend is already up, and the real
    jax.distributed.initialize either detects no cluster (ValueError) or
    refuses post-backend init (benign RuntimeError) — both must no-op."""
    assert initialize_distributed() == 1
    assert initialize_distributed() == 1  # idempotent
    assert is_coordinator()


def test_cluster_detection_is_delegated(monkeypatch):
    """With no args, bare jax.distributed.initialize() runs — JAX's own
    cluster auto-detection (SLURM/MPI/TPU pods) decides."""
    monkeypatch.setattr(distributed, '_already_initialized', lambda: False)
    called = []
    monkeypatch.setattr(jax.distributed, 'initialize',
                        lambda **kw: called.append(kw))
    initialize_distributed()
    assert called == [{}]


@pytest.mark.parametrize('kwargs', [
    dict(coordinator_address='host:1234', num_processes=4, process_id=2),
    dict(process_id=3),  # rank alone must still reach initialize
])
def test_explicit_args_are_forwarded(monkeypatch, kwargs):
    monkeypatch.setattr(distributed, '_already_initialized', lambda: False)
    calls = {}

    def fake_init(coordinator_address=None, num_processes=None,
                  process_id=None):
        calls.update(addr=coordinator_address, n=num_processes,
                     pid=process_id)

    monkeypatch.setattr(jax.distributed, 'initialize', fake_init)
    initialize_distributed(**kwargs)
    assert calls['pid'] == kwargs['process_id']
    assert calls['addr'] == kwargs.get('coordinator_address')


def test_launcher_initialized_runtime_is_benign(monkeypatch):
    """A launcher already called jax.distributed.initialize: the second
    call raises the 'only be called once' RuntimeError, which must be
    swallowed."""
    monkeypatch.setattr(distributed, '_already_initialized', lambda: False)

    def once(**kw):
        raise RuntimeError(
            'jax.distributed.initialize should only be called once.')

    monkeypatch.setattr(jax.distributed, 'initialize', once)
    assert initialize_distributed() == 1


def test_genuine_failures_propagate(monkeypatch):
    monkeypatch.setattr(distributed, '_already_initialized', lambda: False)

    def broken(**kw):
        raise RuntimeError('coordinator unreachable at host:1234')

    monkeypatch.setattr(jax.distributed, 'initialize', broken)
    with pytest.raises(RuntimeError, match='unreachable'):
        initialize_distributed('host:1234', 4, 0)


def test_explicit_path_fails_loudly_after_backend_init(monkeypatch):
    """An explicit multi-process request that cannot be honored (backend
    already up) must raise, not silently degrade to isolated
    single-process jobs."""
    monkeypatch.setattr(distributed, '_already_initialized', lambda: False)

    def late(**kw):
        raise RuntimeError(
            'jax.distributed.initialize() must be called before any JAX '
            'calls that might initialise the XLA backend')

    monkeypatch.setattr(jax.distributed, 'initialize', late)
    with pytest.raises(RuntimeError, match='before any JAX calls'):
        initialize_distributed('host:1234', 4, 0)
