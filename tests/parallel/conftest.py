"""De-flake fixture: the parallel tests must not read the persistent
XLA compilation cache.

Root cause (verified on this container's jax 0.4.37, CPU backend with 8
virtual devices): an executable that BOTH donates inputs
(``make_sharded_train_step`` passes ``donate_argnums=(0,)``) AND is
partitioned over a multi-device mesh round-trips through the persistent
compilation cache with broken input-output aliasing — a cache HIT
deserializes an executable that reads donated buffers after they have
been released, returning nondeterministic garbage (observed: sharded
loss 2.079 / 3.185 / NaN across runs for a true loss of 1.965). A fresh
in-process compile of the very same program is always correct, which is
exactly the order-dependence that made
``test_bn_stats_match_single_device[8]`` and
``test_corr_sharding_matches_unconstrained[-1]`` pass or fail depending
on which earlier run had populated the on-disk cache
(``tests/.jax_compile_cache``, enabled by the root conftest).

The fix is scoped, not global: only this package's tests compile
donating multi-device programs, so only they opt out of the persistent
cache. ``is_cache_used`` latches its decision process-wide on first
use, so the fixture must also ``reset_cache()`` on every transition —
flipping the config flag alone would be silently ignored.
"""

import contextlib

import jax
import pytest


@contextlib.contextmanager
def pinned_partitionable_threefry():
    """Pin the modern RNG partitioning for sharded-lowering assertions.

    The collective-profile tests assert that row-sharded state never
    rides a full-width collective; with the pre-0.5 default
    ``jax_threefry_partitionable=False``, GSPMD materializes each shard's
    random bits at full width and collective-permutes them — an artifact
    of the legacy RNG lowering, not of this repo's sharding. The flag is
    part of jax's trace context (jit caches key on it), so scoping it to
    this package cannot leak compiled programs elsewhere.

    A contextmanager (not only a fixture) because module-scoped fixtures
    lowering HLO set up BEFORE function-scoped autouse fixtures — they
    must pin the flag around their own lowering."""
    prev = jax.config.jax_threefry_partitionable
    jax.config.update('jax_threefry_partitionable', True)
    try:
        yield
    finally:
        jax.config.update('jax_threefry_partitionable', prev)


@pytest.fixture(autouse=True)
def _partitionable_threefry():
    with pinned_partitionable_threefry():
        yield


@pytest.fixture(autouse=True)
def _no_persistent_compile_cache():
    from jax._src import compilation_cache

    prev = jax.config.jax_enable_compilation_cache
    jax.config.update('jax_enable_compilation_cache', False)
    compilation_cache.reset_cache()  # un-latch is_cache_used
    try:
        yield
    finally:
        jax.config.update('jax_enable_compilation_cache', prev)
        compilation_cache.reset_cache()
