"""Mesh-sharded top-k must be bit-identical to the dense reference
semantics, ties included, on the virtual 8-device CPU mesh."""

import jax.numpy as jnp
import numpy as np
import pytest

from dgmc_tpu.ops import dense_topk
from dgmc_tpu.parallel import (make_mesh, sharded_topk_rows,
                               sharded_topk_cols)


@pytest.fixture(scope='module')
def mesh():
    return make_mesh(data=1, model=8)


def _case(B=2, N_s=16, N_t=24, C=8, seed=0, ties=False):
    rng = np.random.RandomState(seed)
    h_s = rng.randn(B, N_s, C).astype(np.float32)
    h_t = rng.randn(B, N_t, C).astype(np.float32)
    if ties:
        # Duplicate target rows so scores collide and tie-break matters.
        h_t = np.repeat(h_t[:, ::2], 2, axis=1)
    t_mask = np.ones((B, N_t), bool)
    t_mask[:, -3:] = False
    return jnp.asarray(h_s), jnp.asarray(h_t), jnp.asarray(t_mask)


@pytest.mark.parametrize('ties', [False, True])
def test_rows_matches_dense(mesh, ties):
    h_s, h_t, t_mask = _case(ties=ties)
    want = dense_topk(h_s, h_t, 5, t_mask=t_mask)
    got = sharded_topk_rows(mesh, h_s, h_t, 5, t_mask=t_mask, block=8)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.parametrize('ties', [False, True])
def test_cols_matches_dense(mesh, ties):
    h_s, h_t, t_mask = _case(ties=ties)
    want = dense_topk(h_s, h_t, 3, t_mask=t_mask)
    got = sharded_topk_cols(mesh, h_s, h_t, 3, t_mask=t_mask)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_cols_rejects_oversized_k(mesh):
    h_s, h_t, t_mask = _case()
    with pytest.raises(ValueError):
        sharded_topk_cols(mesh, h_s, h_t, 4, t_mask=t_mask)  # 24/8=3 < 4


def test_disable_embedded_kernels_is_independent_escape_hatch():
    """disable_fused_kernels() deliberately does NOT reach the
    shard_map-embedded top-k (that region is manual code where the kernel
    is valid); disable_embedded_kernels() is the dedicated opt-out."""
    from dgmc_tpu.ops.pallas.dispatch import (disable_embedded_kernels,
                                              disable_fused_kernels,
                                              embedded_kernels_allowed,
                                              fused_kernels_allowed)
    assert embedded_kernels_allowed()
    with disable_embedded_kernels():
        assert not embedded_kernels_allowed()
        assert fused_kernels_allowed()  # switches are independent
    with disable_fused_kernels():
        assert embedded_kernels_allowed()
    assert embedded_kernels_allowed()


@pytest.mark.parametrize('n_s', [13, 15, 17])
def test_corr_sharded_topk_ragged_rows_stay_live(mesh, n_s):
    """Row counts that do NOT divide the model axis must keep the embedded
    shard_map path (padded rows are discarded work), not fall back to the
    GSPMD scan — KeOps never falls back by shape either (reference
    dgmc.py:85-94). Indices must be bit-identical to dense_topk."""
    from dgmc_tpu.parallel import corr_sharding
    from dgmc_tpu.parallel.topk import corr_sharded_topk

    h_s, h_t, t_mask = _case(B=1, N_s=n_s, ties=True)
    sh = corr_sharding(mesh)
    got = corr_sharded_topk(sh, h_s, h_t, 5, t_mask)
    assert got is not None, 'ragged rows must not fall back'
    assert got.shape == (1, n_s, 5)
    want = dense_topk(h_s, h_t, 5, t_mask=t_mask)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_corr_sharded_topk_ragged_batch_falls_back(mesh):
    """A ragged BATCH axis still declines (padding it would replicate the
    whole per-pair cost)."""
    from dgmc_tpu.parallel import corr_sharding
    from dgmc_tpu.parallel.topk import corr_sharded_topk
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh2 = make_mesh(data=2, model=4)
    h_s, h_t, t_mask = _case(B=3)
    sh = NamedSharding(mesh2, P('data', 'model', None))
    assert corr_sharded_topk(sh, h_s, h_t, 5, t_mask) is None
