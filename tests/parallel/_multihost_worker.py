"""Worker for the 2-process CPU multi-host test (spawned by
``test_multihost.py``). Each process owns 4 virtual CPU devices; together
they form one 8-device data mesh and run sharded train steps on
per-process batch slices, printing the final loss for cross-process
comparison."""

import os
import sys

os.environ['JAX_PLATFORMS'] = 'cpu'
os.environ['XLA_FLAGS'] = (os.environ.get('XLA_FLAGS', '') +
                           ' --xla_force_host_platform_device_count=4')

import jax  # noqa: E402

jax.config.update('jax_platforms', 'cpu')

import numpy as np  # noqa: E402


def main():
    pid, port = int(sys.argv[1]), sys.argv[2]
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    from dgmc_tpu.models import DGMC, GIN
    from dgmc_tpu.ops import GraphBatch
    from dgmc_tpu.parallel import (global_batch, initialize_distributed,
                                   is_coordinator, local_batch_slice,
                                   make_mesh, make_sharded_train_step)
    from dgmc_tpu.train import create_train_state
    from dgmc_tpu.utils.data import PairBatch

    nproc = initialize_distributed(f'localhost:{port}', 2, pid)
    assert nproc == 2, nproc
    assert len(jax.devices()) == 8, jax.devices()
    assert is_coordinator() == (pid == 0)

    B, N, E, C = 8, 12, 30, 16
    rng = np.random.RandomState(0)  # same data on both processes

    def side():
        return GraphBatch(
            x=rng.randn(B, N, C).astype(np.float32),
            senders=rng.randint(0, N, (B, E)).astype(np.int32),
            receivers=rng.randint(0, N, (B, E)).astype(np.int32),
            node_mask=np.ones((B, N), bool),
            edge_mask=np.ones((B, E), bool))

    y = np.tile(np.arange(N, dtype=np.int32), (B, 1))
    batch = PairBatch(s=side(), t=side(), y=y, y_mask=y >= 0)

    model = DGMC(GIN(C, 16, 2), GIN(8, 8, 2), num_steps=2, k=-1)
    state = create_train_state(model, jax.random.key(0), batch,
                               learning_rate=1e-3)

    mesh = make_mesh(data=len(jax.devices()))
    step = make_sharded_train_step(model, mesh, loss_on_s0=True)
    state = global_batch(state, mesh, replicate=True)
    fed = global_batch(local_batch_slice(batch), mesh)

    key = jax.random.key(1)
    out = None
    for _ in range(2):
        key, sub = jax.random.split(key)
        state, out = step(state, fed, sub)
    print(f'LOSS {float(out["loss"]):.6f}', flush=True)


if __name__ == '__main__':
    main()
