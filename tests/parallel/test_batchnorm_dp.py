"""BatchNorm under data parallelism: statistics must be GLOBAL.

VERDICT r3 weak-item 5: a ``batch_norm=True`` backbone under the DP path
must not silently train on per-shard statistics. The sharded train step
is GSPMD-partitioned over a global logical batch
(``parallel/distributed.py:global_batch`` builds global arrays from
process-local slices), so the masked mean/variance reductions in
``MaskedBatchNorm`` span the whole batch and XLA inserts the cross-shard
collectives itself. This test pins that behavior: running statistics
after a sharded step over an 8-way data mesh must match the single-device
step on the same full batch.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dgmc_tpu.models import DGMC, RelCNN
from dgmc_tpu.ops.graph import GraphBatch
from dgmc_tpu.parallel import (make_mesh, make_sharded_train_step,
                               replicate, shard_batch)
from dgmc_tpu.train import create_train_state, make_train_step
from dgmc_tpu.utils.data import PairBatch


def _batch(B=8, n=8, e=20, c=4, seed=0):
    r = np.random.RandomState(seed)

    def side(s):
        rr = np.random.RandomState(s)
        return GraphBatch(
            x=rr.randn(B, n, c).astype(np.float32),
            senders=rr.randint(0, n, (B, e)).astype(np.int32),
            receivers=rr.randint(0, n, (B, e)).astype(np.int32),
            node_mask=rr.rand(B, n) < 0.8,
            edge_mask=np.ones((B, e), bool), edge_attr=None)

    y = np.stack([r.permutation(n) for _ in range(B)]).astype(np.int32)
    return PairBatch(s=side(1), t=side(2), y=y, y_mask=y >= 0)


# The cross-device BN-stat sync contract holds at any device count;
# tier-1 pins it on 2 devices (~1/3 the wall clock), tier-2 repeats
# it at the full virtual-8 mesh.
@pytest.mark.parametrize('ndev', [2,
                                  pytest.param(8,
                                               marks=pytest.mark.slow)])
def test_bn_stats_match_single_device(ndev):
    if len(jax.devices()) < ndev:
        pytest.skip(f'needs {ndev} devices')
    batch = _batch()
    model = DGMC(RelCNN(4, 6, num_layers=1, batch_norm=True),
                 RelCNN(4, 4, num_layers=1), num_steps=1, k=-1)
    state = create_train_state(model, jax.random.key(0), batch,
                               learning_rate=1e-3)
    assert state.batch_stats, 'expected BN running statistics'

    key = jax.random.key(1)
    # Host copy first: both steps donate their input state.
    state_host = jax.tree.map(np.asarray, state)
    single = make_train_step(model)
    s1, out1 = single(state, batch, key)

    mesh = make_mesh(data=ndev, devices=jax.devices()[:ndev])
    sharded = make_sharded_train_step(model, mesh)
    s2, out2 = sharded(replicate(state_host, mesh),
                       shard_batch(batch, mesh), key)

    np.testing.assert_allclose(float(out1['loss']), float(out2['loss']),
                               rtol=1e-5)
    flat1 = jax.tree.leaves(s1.batch_stats)
    flat2 = jax.tree.leaves(s2.batch_stats)
    assert flat1 and len(flat1) == len(flat2)
    for a, b in zip(flat1, flat2):
        # Equal running stats <=> the sharded step reduced mean/var over
        # the GLOBAL batch, not per-shard slices.
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)
