"""True multi-process execution test (VERDICT round-2 item 4).

Launches TWO separate Python processes, each owning 4 virtual CPU
devices, that bring up the JAX distributed runtime against a localhost
coordinator and jointly execute data-parallel sharded train steps over
one 8-device global mesh — per-process batch slices assembled with
``jax.make_array_from_process_local_data`` via
:func:`dgmc_tpu.parallel.global_batch`. Both processes must finish and
agree on the loss.
"""

import os
import re
import socket
import subprocess
import sys

import jax
import pytest

WORKER = os.path.join(os.path.dirname(__file__), '_multihost_worker.py')

# Cross-process collectives on the CPU backend arrived with the
# cpu_collectives_implementation knob (gloo); a jaxlib without it fails
# every multiprocess CPU computation with INVALID_ARGUMENT.
_CPU_MULTIPROCESS = hasattr(jax.config, 'jax_cpu_collectives_implementation')


def _free_port():
    with socket.socket() as s:
        s.bind(('localhost', 0))
        return s.getsockname()[1]


@pytest.mark.skipif(not _CPU_MULTIPROCESS,
                    reason='this jaxlib has no CPU multiprocess '
                           'collectives (no gloo backend)')
def test_two_process_sharded_training():
    port = _free_port()
    env = dict(os.environ)
    env.pop('JAX_PLATFORMS', None)
    procs = [subprocess.Popen(
        [sys.executable, WORKER, str(pid), str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for pid in range(2)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=600)
        outs.append(out)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f'worker failed:\n{out[-3000:]}'
    losses = [float(re.search(r'LOSS ([\d.eE+-]+)', o).group(1))
              for o in outs]
    assert losses[0] == losses[1], losses