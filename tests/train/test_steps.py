"""End-to-end training-step tests on synthetic matchable pairs —
the minimum slice of the reference's example loops (SURVEY.md §7 step 3)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dgmc_tpu.data import (Compose, Constant, KNNGraph, Cartesian,
                           RandomGraphPairs)
from dgmc_tpu.models import DGMC, SplineCNN
from dgmc_tpu.train import (create_train_state, make_train_step,
                            make_eval_step, aggregate_eval)
from dgmc_tpu.utils import PairLoader


def tiny_loader(batch_size=4, length=8, seed=0):
    transform = Compose([Constant(), KNNGraph(k=4), Cartesian()])
    ds = RandomGraphPairs(min_inliers=6, max_inliers=10, min_outliers=0,
                          max_outliers=2, transform=transform, length=length,
                          seed=seed)
    return PairLoader(ds, batch_size, shuffle=True, seed=seed,
                      num_nodes=12, num_edges=48)


def tiny_model(k=-1, num_steps=2):
    # SplineCNN reads the Cartesian edge pseudo-coordinates — the geometric
    # signal of this synthetic task (as in reference examples/pascal_pf.py).
    psi_1 = SplineCNN(1, 16, dim=2, num_layers=2, cat=False, lin=True)
    psi_2 = SplineCNN(8, 8, dim=2, num_layers=2, cat=True, lin=True)
    return DGMC(psi_1, psi_2, num_steps=num_steps, k=k)


@pytest.mark.parametrize('k', [-1, 4])
def test_train_step_learns(k):
    model = tiny_model(k=k)
    loader = tiny_loader()
    batch0 = next(iter(loader))
    state = create_train_state(model, jax.random.key(0), batch0,
                               learning_rate=1e-2)
    step = make_train_step(model, loss_on_s0=True)

    losses = []
    key = jax.random.key(1)
    for epoch in range(10):
        for batch in loader:
            key, sub = jax.random.split(key)
            state, out = step(state, batch, sub)
            losses.append(float(out['loss']))
            assert np.isfinite(losses[-1])
    # Learning happened: the tail is clearly below the head.
    assert np.mean(losses[-4:]) < np.mean(losses[:4])


def test_two_phase_schedule_shares_state():
    """Phase 1 (num_steps=0) and phase 2 (num_steps>0, detach) run against
    the same TrainState — the explicit-config version of the reference's
    DBP15K schedule (reference examples/dbp15k.py:63-69)."""
    model = tiny_model(k=4, num_steps=2)
    loader = tiny_loader()
    batch = next(iter(loader))
    state = create_train_state(model, jax.random.key(0), batch)
    phase1 = make_train_step(model, num_steps=0)
    phase2 = make_train_step(model, num_steps=2, detach=True)

    state, out1 = phase1(state, batch, jax.random.key(1))
    state, out2 = phase2(state, batch, jax.random.key(2))
    assert np.isfinite(float(out1['loss']))
    assert np.isfinite(float(out2['loss']))


def test_detach_cuts_psi1_gradients():
    model = tiny_model(k=-1)
    loader = tiny_loader()
    batch = next(iter(loader))
    state = create_train_state(model, jax.random.key(0), batch)

    from dgmc_tpu.models import metrics

    def loss_fn(params, detach):
        (S_0, S_L) = model.apply(
            {'params': params}, batch.s, batch.t, train=False,
            num_steps=2, detach=detach,
            rngs={'noise': jax.random.key(3)})
        # Only the refined loss: with detach, psi_1 gets zero gradient.
        return metrics.nll_loss(S_L, batch.y, batch.y_mask)

    g = jax.grad(loss_fn)(state.params, True)
    psi1_norm = sum(jnp.abs(v).sum()
                    for v in jax.tree.leaves(g['psi_1']))
    assert float(psi1_norm) == 0.0
    g2 = jax.grad(loss_fn)(state.params, False)
    psi1_norm2 = sum(jnp.abs(v).sum()
                     for v in jax.tree.leaves(g2['psi_1']))
    assert float(psi1_norm2) > 0.0


def test_eval_step_and_aggregate():
    model = tiny_model(k=-1)
    loader = tiny_loader()
    batch = next(iter(loader))
    state = create_train_state(model, jax.random.key(0), batch)
    ev = make_eval_step(model, hits_ks=(1, 3))

    totals = [ev(state, b, jax.random.key(i))
              for i, b in enumerate(loader)]
    agg = aggregate_eval([jax.tree.map(float, t) for t in totals])
    assert 0.0 <= agg['acc'] <= 1.0
    assert agg['hits@1'] == pytest.approx(agg['acc'])
    assert agg['hits@3'] >= agg['hits@1']
    assert agg['count'] > 0
