"""Checkpoint/resume and the in-memory snapshot (willow transfer) protocol."""

import jax
import numpy as np

from dgmc_tpu.train import (Checkpointer, create_train_state, make_train_step,
                            restore_params, snapshot_params)

from tests.train.test_steps import tiny_loader, tiny_model


def _tree_equal(a, b):
    flat_a, flat_b = jax.tree.leaves(a), jax.tree.leaves(b)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(flat_a, flat_b))


def test_checkpoint_roundtrip(tmp_path):
    model = tiny_model()
    loader = tiny_loader()
    batch = next(iter(loader))
    state = create_train_state(model, jax.random.key(0), batch)
    step = make_train_step(model)
    state, _ = step(state, batch, jax.random.key(1))

    ckpt = Checkpointer(tmp_path / 'ckpt')
    ckpt.save(1, state, wait=True)
    assert ckpt.latest_step() == 1

    # Restore into a freshly-initialized state (different values).
    fresh = create_train_state(model, jax.random.key(7), batch)
    assert not _tree_equal(fresh.params, state.params)
    restored = ckpt.restore(fresh)
    assert _tree_equal(restored.params, state.params)
    assert _tree_equal(restored.opt_state, state.opt_state)
    ckpt.close()


def test_snapshot_restore_params():
    """The willow protocol: pretrain -> snapshot -> N runs each restoring the
    snapshot with a fresh optimizer (reference examples/willow.py:90,155)."""
    model = tiny_model()
    loader = tiny_loader()
    batch = next(iter(loader))
    state = create_train_state(model, jax.random.key(0), batch)
    step = make_train_step(model)
    state, _ = step(state, batch, jax.random.key(1))

    snap = snapshot_params(state)
    state2, _ = step(state, batch, jax.random.key(2))
    assert not _tree_equal(state2.params, snap['params'])

    rolled = restore_params(state2, snap)
    assert _tree_equal(rolled.params, snap['params'])
    assert rolled.step == 0  # fresh optimizer

    # Multi-run protocol: training the restored state (whose buffers the
    # jitted step donates) must not invalidate the snapshot for later runs.
    rolled, _ = step(rolled, batch, jax.random.key(3))
    rolled2 = restore_params(rolled, snap)
    assert _tree_equal(rolled2.params, snap['params'])
