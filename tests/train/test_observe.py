"""Observability units: timer fencing, JSONL metric sink, trace no-op."""

import json

import jax.numpy as jnp

from dgmc_tpu.train import MetricLogger, StepTimer, trace


def test_step_timer_fences_and_summarizes():
    t = StepTimer()
    for i in range(3):
        t.start()
        x = jnp.ones((8, 8)) * i
        t.stop(fence=x.sum())
    s = t.summary()
    assert s['steps'] == 3
    assert s['mean_s'] > 0 and s['max_s'] >= s['p50_s']


def test_metric_logger_writes_jsonl(tmp_path):
    path = tmp_path / 'm.jsonl'
    with MetricLogger(str(path)) as log:
        log.log(1, loss=jnp.float32(0.5), acc=0.25, phase=1)
        log.log(2, loss=0.4)
    recs = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert [r['step'] for r in recs] == [1, 2]
    assert recs[0]['loss'] == 0.5 and recs[0]['phase'] == 1
    assert 'time' in recs[1]


def test_metric_logger_disabled_is_noop():
    log = MetricLogger(None)
    log.log(1, loss=0.1)  # must not raise or create anything
    log.close()


def test_trace_noop_without_dir():
    with trace(None):
        pass


def test_trace_writes_profile(tmp_path):
    d = tmp_path / 'prof'
    with trace(str(d)):
        jnp.ones((4, 4)).sum().block_until_ready()
    assert any(d.rglob('*'))
