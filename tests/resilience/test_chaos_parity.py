"""The acceptance-criterion chaos matrix, end-to-end through the real
dbp15k CLI (synthetic data, tiny shapes): a supervised run SIGKILLed at
a random mid-training step must auto-resume and finish with EXACTLY the
state an uninterrupted run reaches — the per-epoch PRNG stream is
consumed positionally, so determinism is exact, not approximate. The
remaining injected faults each get their recovery path proven the same
way.
"""

import json
import os
import signal
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: Tiny synthetic DBP15K: 2 phase-1 + 3 phase-2 epochs, ckpt every epoch.
SYN = ['--synthetic', '--syn_nodes_s', '48', '--syn_nodes_t', '64',
       '--syn_edges_s', '160', '--syn_edges_t', '224', '--syn_dim', '16',
       '--dim', '16', '--rnd_dim', '8', '--num_layers', '1',
       '--num_steps', '2', '--k', '5', '--epochs', '6',
       '--phase1_epochs', '3', '--ckpt_every', '1', '--seed', '11']


def _run(tmp_path, tag, extra, timeout=900, expect_rc=0):
    env = dict(os.environ, JAX_PLATFORMS='cpu',
               JAX_ENABLE_COMPILATION_CACHE='false')
    log = tmp_path / f'{tag}.log'
    with open(log, 'w') as fh:   # file, not pipe: no deadlock on chatter
        proc = subprocess.run(
            [sys.executable, '-m', 'dgmc_tpu.experiments.dbp15k'] + SYN
            + ['--ckpt_dir', str(tmp_path / f'ck_{tag}'),
               '--metrics_log', str(tmp_path / f'{tag}.jsonl')] + extra,
            cwd=REPO, env=env, stdout=fh, stderr=subprocess.STDOUT,
            timeout=timeout)
    out = log.read_text()
    assert proc.returncode == expect_rc, (tag, proc.returncode,
                                          out[-3000:])
    return out


def _final_state_leaves(ckpt_dir):
    import numpy as np
    import orbax.checkpoint as ocp
    mgr = ocp.CheckpointManager(str(ckpt_dir))
    step = mgr.latest_step()
    tree = mgr.restore(step, args=ocp.args.StandardRestore())
    mgr.close()
    import jax
    return step, [np.asarray(leaf) for leaf in jax.tree.leaves(tree)]


def _metrics(tmp_path, tag):
    with open(tmp_path / f'{tag}.jsonl') as f:
        return [json.loads(line) for line in f]


def _supervised(extra_faults, obs_tag):
    return ['--supervise', '--max-restarts', '3',
            '--restart-backoff', '0.1',
            '--obs-dir'] + [obs_tag] + extra_faults


@pytest.mark.slow
def test_sigkill_chaos_parity(tmp_path):
    """The headline: SIGKILL at a mid-training step under --supervise ==
    an uninterrupted run, exactly, down to every state leaf."""
    import numpy as np
    _run(tmp_path, 'control', [])

    obs = str(tmp_path / 'obs')
    # "Random mid-training step", reproducibly: seeded draw over the
    # epochs that have both a predecessor checkpoint and a successor.
    import random
    kill_epoch = random.Random(11).randint(2, 5)
    out = _run(tmp_path, 'chaos',
               _supervised(['--inject-fault', f'sigkill@{kill_epoch}'],
                           obs))
    assert f'firing sigkill@{kill_epoch}' in out
    assert 'Resumed from' in out
    assert '[supervisor] complete' in out

    rec = json.load(open(os.path.join(obs, 'recovery.json')))
    assert rec['outcome'] == 'completed'
    assert rec['restarts'] == 1
    assert rec['attempts'][0]['reason'] == 'signal:SIGKILL'

    # Exact final-state parity, every leaf (params, optimizer, stats).
    step_a, leaves_a = _final_state_leaves(tmp_path / 'ck_control')
    step_b, leaves_b = _final_state_leaves(tmp_path / 'ck_chaos')
    assert step_a == step_b == 6
    assert len(leaves_a) == len(leaves_b)
    for x, y in zip(leaves_a, leaves_b):
        np.testing.assert_array_equal(x, y)

    # Metric parity on the epochs after the kill (the resumed stream).
    tail = lambda tag: [(m['step'], m.get('loss'), m.get('hits1'))
                       for m in _metrics(tmp_path, tag)
                       if m.get('loss') is not None and m['step'] >= 4]
    assert tail('chaos')[-3:] == tail('control')[-3:]

    # The recovery timeline renders through obs.report.
    rep = subprocess.run(
        [sys.executable, '-m', 'dgmc_tpu.obs.report', obs],
        cwd=REPO, capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS='cpu'), timeout=120)
    assert rep.returncode == 0, rep.stderr[-2000:]
    assert 'recovery timeline' in rep.stdout
    assert 'signal:SIGKILL' in rep.stdout


@pytest.mark.slow
def test_sigterm_and_raise_recovery(tmp_path):
    """Preemption (SIGTERM) at one epoch and a crashing exception at a
    later one, both in one supervised run: two restarts, then done."""
    obs = str(tmp_path / 'obs')
    out = _run(tmp_path, 'chaos',
               _supervised(['--inject-fault', 'sigterm@2',
                            '--inject-fault', 'raise@4'], obs))
    assert 'firing sigterm@2' in out and 'firing raise@4' in out
    rec = json.load(open(os.path.join(obs, 'recovery.json')))
    assert rec['outcome'] == 'completed'
    assert rec['restarts'] == 2
    step, _leaves = _final_state_leaves(tmp_path / 'ck_chaos')
    assert step == 6


@pytest.mark.slow
def test_stall_hang_is_killed_and_resumed(tmp_path):
    """A wedged step (the rc:124 multichip failure mode): the child's
    watchdog heartbeat goes stale, the supervisor kills it, and the
    restarted run completes. The stall outlives 2x the deadline but not
    the test: the injected sleep is the only thing keeping attempt 0
    alive, so the SIGKILL escalation reaps it immediately."""
    obs = str(tmp_path / 'obs')
    out = _run(tmp_path, 'chaos',
               _supervised(['--inject-fault', 'stall@4:600',
                            '--watchdog-deadline', '5'], obs),
               timeout=900)
    assert 'firing stall@4' in out
    rec = json.load(open(os.path.join(obs, 'recovery.json')))
    assert rec['outcome'] == 'completed'
    assert rec['attempts'][0]['reason'] in ('heartbeat-stale',
                                            'hang-report')
    step, _leaves = _final_state_leaves(tmp_path / 'ck_chaos')
    assert step == 6


@pytest.mark.slow
def test_ckpt_corrupt_fault_resumes_from_previous(tmp_path):
    """ckpt-corrupt@3 + sigkill@5: the restarted attempt finds its
    latest intact checkpoint (4), or — had 4 been the damaged one —
    falls back; either way it completes with full-length training."""
    obs = str(tmp_path / 'obs')
    out = _run(tmp_path, 'chaos',
               _supervised(['--inject-fault', 'ckpt-corrupt@4',
                            '--inject-fault', 'sigkill@5'], obs))
    assert 'damaged' in out          # the fault hit a real file
    assert 'failed verification' in out or 'failed to restore' in out
    rec = json.load(open(os.path.join(obs, 'recovery.json')))
    assert rec['outcome'] == 'completed'
    step, _leaves = _final_state_leaves(tmp_path / 'ck_chaos')
    assert step == 6


@pytest.mark.slow
def test_nan_grads_skips_and_reports(tmp_path):
    """nan-grads@5 under --guard-bad-steps: the poisoned step is skipped
    (params frozen for it), training continues, and the skip ledger
    lands in the metrics log."""
    _run(tmp_path, 'guarded',
         ['--inject-fault', 'nan-grads@5', '--guard-bad-steps', '3'])
    metrics = _metrics(tmp_path, 'guarded')
    final = [m for m in metrics if m.get('skipped_steps') is not None][-1]
    assert final['skipped_steps'] == 1
    assert final['consec_bad'] == 0  # recovered by the next good step
