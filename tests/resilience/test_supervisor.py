"""The run supervisor against a scriptable toy child: crash restarts,
heartbeat-stale and hang-report kills, the restart budget, and the
graceful-degradation ladder. jax-free on both sides — the monitor loop
must work in any process, and these tests pin exactly the behaviors the
slow chaos tests then exercise through the real CLIs.
"""

import json
import os
import signal
import subprocess
import sys
import time

from dgmc_tpu.resilience.supervisor import (Supervisor,
                                            strip_supervisor_args,
                                            _flag_value,
                                            _replace_flag_value,
                                            LADDER_RUNGS)

#: A child whose per-attempt behavior is scripted by a JSON plan file:
#: ``{"attempts": [{"action": "crash"|"hang"|"hang-report"|"ok",
#: "steps": N}, ...]}`` — attempt index persists in a counter file, the
#: child dumps its argv+env evidence per attempt, writes a heartbeat
#: like the real watchdog thread would, then acts.
CHILD = r'''
import json, os, sys, time
plan_path, counter_path = sys.argv[1], sys.argv[2]
argv = sys.argv[3:]
obs_dir = None
for i, tok in enumerate(argv):
    if tok in ('--obs-dir', '--obs_dir'):
        obs_dir = argv[i + 1]
k = 0
if os.path.exists(counter_path):
    k = json.load(open(counter_path))['attempt'] + 1
json.dump({'attempt': k}, open(counter_path, 'w'))
plan = json.load(open(plan_path))['attempts']
me = plan[min(k, len(plan) - 1)]
if me['action'] == 'wedge-early':
    time.sleep(120)   # wedged BEFORE the watchdog thread ever arms:
                      # no heartbeat, no hang_report, ever
if obs_dir:
    os.makedirs(obs_dir, exist_ok=True)
    json.dump({'argv': argv,
               'DGMC_TPU_DISABLE_FUSED':
                   os.environ.get('DGMC_TPU_DISABLE_FUSED')},
              open(os.path.join(obs_dir, 'evidence.json'), 'w'))
    json.dump({'time': time.time(), 'pid': os.getpid(),
               'steps_completed': me.get('steps', k)},
              open(os.path.join(obs_dir, 'heartbeat.json'), 'w'))
ckpt_dir = None
for i, tok in enumerate(argv):
    if tok in ('--ckpt_dir', '--ckpt-dir'):
        ckpt_dir = argv[i + 1]
if ckpt_dir and me.get('ckpt_step') is not None:
    os.makedirs(os.path.join(ckpt_dir, str(me['ckpt_step'])),
                exist_ok=True)
action = me['action']
if action == 'crash':
    sys.exit(me.get('rc', 3))
if action == 'kill-self':
    import signal
    os.kill(os.getpid(), signal.SIGKILL)
if action == 'hang':
    time.sleep(120)   # heartbeat never refreshes -> goes stale
if action == 'hang-report':
    json.dump({'reason': 'deadline: no event for 600.0s'},
              open(os.path.join(obs_dir, 'hang_report.json'), 'w'))
    time.sleep(120)
sys.exit(0)
'''


def _supervise(tmp_path, attempts, *, argv=(), max_restarts=5,
               hang_deadline_s=None, ladder=(), **kw):
    child = tmp_path / 'child.py'
    child.write_text(CHILD)
    plan = tmp_path / 'plan.json'
    plan.write_text(json.dumps({'attempts': attempts}))
    obs = tmp_path / 'obs'
    sup = Supervisor(
        [sys.executable, str(child), str(plan),
         str(tmp_path / 'counter.json')],
        list(argv) + ['--obs-dir', str(obs)],
        obs_dir=str(obs), max_restarts=max_restarts, backoff_s=0.05,
        grace_s=2.0, poll_s=0.05, hang_deadline_s=hang_deadline_s,
        ladder=ladder, **kw)
    rc = sup.run()
    recovery = json.load(open(obs / 'recovery.json'))
    return rc, recovery, obs


def _evidence(obs, attempt):
    return json.load(open(obs / f'attempt_{attempt}' / 'evidence.json'))


def test_completes_clean_without_restart(tmp_path):
    rc, rec, _obs = _supervise(tmp_path, [{'action': 'ok'}])
    assert rc == 0
    assert rec['outcome'] == 'completed'
    assert rec['restarts'] == 0
    assert [a['reason'] for a in rec['attempts']] == ['completed']


def test_crashes_restart_until_success(tmp_path):
    rc, rec, obs = _supervise(
        tmp_path,
        [{'action': 'crash'}, {'action': 'crash'}, {'action': 'ok'}])
    assert rc == 0
    assert rec['outcome'] == 'completed'
    assert rec['restarts'] == 2
    assert [a['reason'] for a in rec['attempts']] == \
        ['exit:3', 'exit:3', 'completed']
    # Per-attempt telemetry is isolated: --obs-dir rewritten per attempt.
    for k in range(3):
        ev = _evidence(obs, k)
        assert ev['argv'][-1].endswith(f'attempt_{k}')


def test_death_by_signal_is_recorded_and_retried(tmp_path):
    """SIGKILL (what a preempted or OOM-killed child looks like) is
    attributed by signal name and retried like any crash."""
    rc, rec, _obs = _supervise(
        tmp_path, [{'action': 'kill-self'}, {'action': 'ok'}])
    assert rc == 0
    assert rec['outcome'] == 'completed'
    assert rec['attempts'][0]['reason'] == 'signal:SIGKILL'


def test_restart_budget_exhaustion_gives_up(tmp_path):
    rc, rec, _obs = _supervise(
        tmp_path, [{'action': 'crash', 'rc': 7}], max_restarts=2)
    assert rc == 7
    assert rec['outcome'] == 'gave-up'
    assert rec['restarts'] == 3  # initial + 2 restarts, all failed
    assert [a['reason'] for a in rec['attempts']] == ['exit:7'] * 3
    assert any(e['event'] == 'give-up' for e in rec['events'])


def test_stale_heartbeat_kills_and_restarts(tmp_path):
    rc, rec, _obs = _supervise(
        tmp_path, [{'action': 'hang'}, {'action': 'ok'}],
        hang_deadline_s=0.3)
    assert rc == 0
    assert rec['outcome'] == 'completed'
    assert rec['attempts'][0]['reason'] == 'heartbeat-stale'
    assert rec['attempts'][1]['reason'] == 'completed'


def test_hang_report_kills_and_restarts(tmp_path):
    """A deadline hang_report.json appearing in the attempt dir is the
    in-process watchdog screaming; the supervisor must kill + restart
    without waiting for the heartbeat to also go stale."""
    rc, rec, _obs = _supervise(
        tmp_path, [{'action': 'hang-report'}, {'action': 'ok'}],
        hang_deadline_s=600.0)
    assert rc == 0
    assert rec['attempts'][0]['reason'] == 'hang-report'
    assert rec['outcome'] == 'completed'


def test_degradation_ladder_escalates_on_same_step(tmp_path):
    """Three crashes at the SAME step: after the second, the ladder's
    first rung must fire (fused kernels off via env), after the third
    the second rung (--f32). A different-step crash does not escalate."""
    rc, rec, obs = _supervise(
        tmp_path,
        [{'action': 'crash', 'steps': 5}, {'action': 'crash', 'steps': 5},
         {'action': 'crash', 'steps': 5}, {'action': 'ok', 'steps': 5}],
        ladder=('disable-fused', 'f32', 'shrink-mesh'),
        argv=['--model_shards', '4'])
    assert rc == 0
    assert rec['outcome'] == 'completed'
    rungs = [d['rung'] for d in rec['degradations']]
    assert rungs == ['disable-fused', 'f32']
    # Attempt 0/1 ran clean; the rungs appear in later attempts' env/argv.
    assert _evidence(obs, 0)['DGMC_TPU_DISABLE_FUSED'] is None
    assert '--f32' not in _evidence(obs, 1)['argv']
    assert _evidence(obs, 2)['DGMC_TPU_DISABLE_FUSED'] == '1'
    assert '--f32' in _evidence(obs, 3)['argv']
    # shrink-mesh never fired (budget recovered before rung 3).
    assert _flag_value(_evidence(obs, 3)['argv'],
                       ('--model_shards',)) == '4'


def test_progressing_preemptions_do_not_degrade(tmp_path):
    """Heartbeat step counts are per-PROCESS and reset on every restart:
    a run making checkpoint progress between repeated preemptions must
    not read as stuck at one step (global step = resumed-from checkpoint
    step + local count), so the ladder stays untouched and the run just
    restarts."""
    ck = tmp_path / 'ck'
    rc, rec, _obs = _supervise(
        tmp_path,
        [{'action': 'crash', 'steps': 5, 'ckpt_step': 5},
         {'action': 'crash', 'steps': 5, 'ckpt_step': 10},
         {'action': 'crash', 'steps': 5, 'ckpt_step': 15},
         {'action': 'ok', 'steps': 5}],
        ladder=('disable-fused', 'f32', 'shrink-mesh'),
        argv=['--ckpt_dir', str(ck)], ckpt_dir=str(ck))
    assert rc == 0
    assert rec['outcome'] == 'completed'
    assert rec['degradations'] == []
    assert [a['steps_completed'] for a in rec['attempts']] == \
        [5, 10, 15, 20]


def test_f32_rung_skips_already_f32_spellings():
    """Any spelling of an already-f32 run (--f32, --precision f32,
    --precision=f32) must not burn the rung on a no-op rewrite."""
    for argv in (['--f32'], ['--precision', 'f32'], ['--precision=f32']):
        out, _env, desc = LADDER_RUNGS['f32'](list(argv), {})
        assert desc is None and out == argv
    out, _env, desc = LADDER_RUNGS['f32']([], {})
    assert '--f32' in out and desc


def test_shrink_mesh_rung_halves_model_shards():
    argv, env, desc = LADDER_RUNGS['shrink-mesh'](
        ['--model_shards', '8'], {})
    assert _flag_value(argv, ('--model_shards',)) == '4'
    assert '8 -> 4' in desc
    # Floor: a 1-shard mesh cannot shrink; the rung reports nothing.
    argv, env, desc = LADDER_RUNGS['shrink-mesh'](
        ['--model_shards', '1'], {})
    assert desc is None


def test_no_first_heartbeat_is_bounded(tmp_path):
    """A child wedged BEFORE its watchdog thread exists (imports, a
    distributed init whose peer never joins) writes neither heartbeat
    nor hang_report: the benefit of the doubt must be bounded, not an
    eternal proc.wait."""
    rc, rec, _obs = _supervise(
        tmp_path, [{'action': 'wedge-early'}, {'action': 'ok'}],
        hang_deadline_s=0.3, first_heartbeat_s=1.0)
    assert rc == 0
    assert rec['outcome'] == 'completed'
    assert rec['attempts'][0]['reason'] == 'no-first-heartbeat'
    assert rec['attempts'][1]['reason'] == 'completed'


def test_supervisor_provides_fault_ledger_home(tmp_path, monkeypatch):
    """A supervised run with NEITHER --ckpt_dir nor --obs-dir still
    needs fire-once fault semantics: the supervisor exports the
    recovery dir as the ledger home and faults.ledger_dir picks it up."""
    from dgmc_tpu.resilience.faults import LEDGER_ENV, ledger_dir
    obs = tmp_path / 'obs'
    sup = Supervisor([sys.executable, '-c', 'pass'], [],
                     obs_dir=str(obs))
    assert sup._base_env[LEDGER_ENV] == str(obs)
    monkeypatch.delenv(LEDGER_ENV, raising=False)
    assert ledger_dir(None, None) is None
    monkeypatch.setenv(LEDGER_ENV, str(obs))
    assert ledger_dir(None, None) == str(obs)
    # Explicit dirs still outrank the env fallback.
    assert ledger_dir('ck', None) == 'ck'
    assert ledger_dir(None, str(obs / 'attempt_3')) == str(obs)


def test_transient_spawn_failure_retries_within_budget(tmp_path,
                                                       monkeypatch):
    """A failed fork/exec (EAGAIN under memory pressure) is a transient
    failure like any crash: it must consume a restart + backoff, not
    give up instantly with budget still available."""
    import dgmc_tpu.resilience.supervisor as sup_mod
    real_popen = subprocess.Popen
    calls = {'n': 0}

    def flaky_popen(*a, **kw):
        calls['n'] += 1
        if calls['n'] == 1:
            raise OSError(11, 'Resource temporarily unavailable')
        return real_popen(*a, **kw)

    monkeypatch.setattr(sup_mod.subprocess, 'Popen', flaky_popen)
    rc, rec, _obs = _supervise(tmp_path, [{'action': 'ok'}])
    assert rc == 0
    assert rec['outcome'] == 'completed'
    assert rec['restarts'] == 1
    assert rec['attempts'][0]['reason'].startswith('spawn-failed')
    assert rec['attempts'][1]['reason'] == 'completed'


def test_persistent_spawn_failure_exhausts_budget(tmp_path):
    obs = tmp_path / 'obs'
    sup = Supervisor(['/nonexistent-interpreter'],
                     ['--obs-dir', str(obs)], obs_dir=str(obs),
                     max_restarts=1, backoff_s=0.01, poll_s=0.05)
    rc = sup.run()
    assert rc == 1
    rec = json.load(open(obs / 'recovery.json'))
    assert rec['outcome'] == 'gave-up'
    assert len(rec['attempts']) == 2
    assert all(a['reason'].startswith('spawn-failed')
               for a in rec['attempts'])


def test_stale_evidence_from_previous_session_is_cleared(tmp_path):
    """A re-run under the same --obs-dir restarts attempt numbering at
    0, so a previous session's deadline hang_report.json and hours-old
    heartbeat.json are sitting in attempt_0 when the new child spawns.
    They must be cleared pre-spawn, not read as this child's liveness
    evidence — otherwise the supervisor kills a healthy child on its
    first poll and can burn the whole restart budget."""
    obs = tmp_path / 'obs'
    stale = obs / 'attempt_0'
    os.makedirs(stale / 'host_0')
    json.dump({'reason': 'deadline: no event for 600.0s'},
              open(stale / 'hang_report.json', 'w'))
    json.dump({'time': time.time() - 3600, 'steps_completed': 1},
              open(stale / 'heartbeat.json', 'w'))
    json.dump({'time': time.time() - 3600, 'steps_completed': 1},
              open(stale / 'host_0' / 'heartbeat.json', 'w'))
    rc, rec, _obs = _supervise(
        tmp_path, [{'action': 'ok'}], hang_deadline_s=0.3)
    assert rc == 0
    assert rec['outcome'] == 'completed'
    assert rec['restarts'] == 0
    assert [a['reason'] for a in rec['attempts']] == ['completed']


def test_supervisor_preempted_forwards_signal(tmp_path):
    """SIGTERM to the SUPERVISOR (scheduler preemption of the monitor
    itself) kills the child and exits 128+signum without restarting."""
    child = tmp_path / 'child.py'
    child.write_text(CHILD)
    plan = tmp_path / 'plan.json'
    plan.write_text(json.dumps({'attempts': [{'action': 'hang'}]}))
    obs = tmp_path / 'obs'
    driver = tmp_path / 'driver.py'
    driver.write_text(f'''
import sys
sys.path.insert(0, {str(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))!r})
from dgmc_tpu.resilience.supervisor import Supervisor
sup = Supervisor([sys.executable, {str(child)!r}, {str(plan)!r},
                  {str(tmp_path / 'counter.json')!r}],
                 ['--obs-dir', {str(obs)!r}], obs_dir={str(obs)!r},
                 backoff_s=0.05, poll_s=0.05, grace_s=2.0)
print('READY', flush=True)
sys.exit(sup.run())
''')
    proc = subprocess.Popen([sys.executable, str(driver)],
                            stdout=subprocess.PIPE, text=True)
    try:
        assert proc.stdout.readline().strip() == 'READY'
        # Give the supervisor a beat to spawn the child, then preempt.
        deadline = time.time() + 20
        while time.time() < deadline and not (
                obs / 'attempt_0' / 'heartbeat.json').exists():
            time.sleep(0.05)
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    assert rc == 128 + signal.SIGTERM
    rec = json.load(open(obs / 'recovery.json'))
    assert rec['outcome'] == 'preempted'


# -- argv surgery ----------------------------------------------------------

def test_strip_supervisor_args():
    assert strip_supervisor_args(
        ['--epochs', '3', '--supervise', '--max-restarts', '2',
         '--restart-backoff', '0.5', '--obs-dir', 'x']) == \
        ['--epochs', '3', '--obs-dir', 'x']
    assert strip_supervisor_args(['--max_restarts=9', 'pos']) == ['pos']


def test_replace_flag_value_both_syntaxes():
    assert _replace_flag_value(['--obs-dir', 'a', '--epochs', '2'],
                               ('--obs-dir', '--obs_dir'), 'b') == \
        ['--obs-dir', 'b', '--epochs', '2']
    assert _replace_flag_value(['--obs_dir=a'], ('--obs-dir', '--obs_dir'),
                               'b') == ['--obs_dir=b']
    # Absent flag: appended.
    assert _replace_flag_value(['--epochs', '2'], ('--obs-dir',), 'b') == \
        ['--epochs', '2', '--obs-dir', 'b']


def test_flag_value_reads_both_syntaxes():
    assert _flag_value(['--model_shards', '8'], ('--model_shards',)) == '8'
    assert _flag_value(['--model_shards=8'], ('--model_shards',)) == '8'
    assert _flag_value([], ('--model_shards',)) is None
