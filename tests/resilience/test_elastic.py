"""Elastic recovery: distributed failures shrink the mesh.

Fast half (jax-free): scriptable toy children + hand-written control
files drive the supervisor's distributed-failure classification, the
elastic shrink, the ledger decision, and the 2-process simulated-host
peer-death path.

Slow half (real dbp15k CLI, synthetic data): ``peer-death@N`` under
``--supervise`` recovers on a shrunk mesh from a RESHARDED checkpoint
and reaches exact final-state parity with an uninterrupted shrunk-mesh
run; ``collective-stall@N`` under ``--fence-deadline`` exits
``FENCE_TIMEOUT_RC`` with a ``hang_report.json`` attributing the fence
— instead of the historical rc:124 silence.
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from dgmc_tpu.resilience.distributed_guard import FENCE_TIMEOUT_RC
from dgmc_tpu.resilience.supervisor import Supervisor, _flag_value

from tests.resilience.test_supervisor import _evidence, _supervise

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _ledger(obs):
    return json.load(open(obs / 'control' / 'ledger.json'))


# -- fast: classification + shrink + ledger --------------------------------

def test_hang_triggers_elastic_shrink(tmp_path):
    """A stale-heartbeat hang is a DISTRIBUTED failure: the mesh flag
    is halved immediately (no same-step ladder wait), the event lands
    in recovery.json, and the leader publishes the decision."""
    rc, rec, obs = _supervise(
        tmp_path, [{'action': 'hang'}, {'action': 'ok'}],
        argv=['--model_shards', '4'], hang_deadline_s=0.3)
    assert rc == 0
    assert rec['outcome'] == 'completed'
    assert rec['attempts'][0]['reason'] == 'heartbeat-stale'
    assert [e['detail'] for e in rec['elastic']] == \
        ['--model_shards 4 -> 2 (shrink the mesh)']
    assert _flag_value(_evidence(obs, 1)['argv'],
                       ('--model_shards',)) == '2'
    led = _ledger(obs)
    assert led['attempt'] == 1 and led['mesh'] == {'shards': 2}
    assert led['decisions'][0]['reason'] == 'heartbeat-stale'


def test_row_shards_spelling_shrinks_too(tmp_path):
    rc, rec, obs = _supervise(
        tmp_path, [{'action': 'hang'}, {'action': 'ok'}],
        argv=['--row_shards', '8'], hang_deadline_s=0.3)
    assert rc == 0
    assert rec['elastic'][0]['detail'] == \
        '--row_shards 8 -> 4 (shrink the mesh)'
    assert _flag_value(_evidence(obs, 1)['argv'],
                       ('--row_shards',)) == '4'


def test_fence_timeout_rc_is_distributed(tmp_path):
    """A child that exited FENCE_TIMEOUT_RC (its fence guard fired) is
    classified as a distributed failure and shrinks the mesh."""
    rc, rec, obs = _supervise(
        tmp_path,
        [{'action': 'crash', 'rc': FENCE_TIMEOUT_RC}, {'action': 'ok'}],
        argv=['--model_shards', '2'])
    assert rc == 0
    assert rec['attempts'][0]['reason'] == f'exit:{FENCE_TIMEOUT_RC}'
    assert rec['elastic'][0]['detail'] == \
        '--model_shards 2 -> 1 (shrink the mesh)'


def test_plain_crash_does_not_shrink(tmp_path):
    """An ordinary crash retries on the SAME mesh: elastic restarts are
    reserved for failures that mean the mesh itself broke."""
    rc, rec, obs = _supervise(
        tmp_path, [{'action': 'crash'}, {'action': 'ok'}],
        argv=['--model_shards', '4'])
    assert rc == 0
    assert rec['elastic'] == []
    assert _flag_value(_evidence(obs, 1)['argv'],
                       ('--model_shards',)) == '4'


def test_no_elastic_opt_out(tmp_path):
    rc, rec, _obs = _supervise(
        tmp_path, [{'action': 'hang'}, {'action': 'ok'}],
        argv=['--model_shards', '4'], hang_deadline_s=0.3,
        elastic=False)
    assert rc == 0
    assert rec['elastic'] == []


def test_unshrinkable_mesh_falls_through_to_retry(tmp_path):
    """No mesh flag (or already 1 shard): a distributed failure still
    just restarts — there is nothing to shrink."""
    rc, rec, _obs = _supervise(
        tmp_path, [{'action': 'hang'}, {'action': 'ok'}],
        argv=['--model_shards', '1'], hang_deadline_s=0.3)
    assert rc == 0
    assert rec['outcome'] == 'completed'
    assert rec['elastic'] == []


def test_peer_death_tombstone_reclassifies_sigkill(tmp_path):
    """The injected peer-death fault SIGKILLs right after writing its
    tombstone; the supervisor must read the tombstone post-mortem and
    classify the death as a peer's, not the run's."""
    # A dedicated toy: beat as host 0, tombstone host 1, die by SIGKILL.
    child = tmp_path / 'child.py'
    child.write_text(r'''
import json, os, signal, sys, time
argv = sys.argv[1:]
obs_dir = argv[argv.index('--obs-dir') + 1]
k_path = os.path.join(os.path.dirname(obs_dir.rstrip('/')), 'k.json')
k = 0
if os.path.exists(k_path):
    k = json.load(open(k_path))['k'] + 1
json.dump({'k': k}, open(k_path, 'w'))
os.makedirs(obs_dir, exist_ok=True)
json.dump({'argv': argv}, open(os.path.join(obs_dir, 'evidence.json'),
                               'w'))
cdir = os.path.join(obs_dir, 'control')
os.makedirs(cdir, exist_ok=True)
json.dump({'host': 0, 'pid': os.getpid(), 'time': time.time(),
           'phase': 'step', 'step': 3},
          open(os.path.join(cdir, 'host_0.json'), 'w'))
if k == 0:
    json.dump({'host': 1, 'time': time.time(), 'step': 3,
               'reason': 'peer-death'},
              open(os.path.join(cdir, 'host_1.tombstone.json'), 'w'))
    os.kill(os.getpid(), signal.SIGKILL)
sys.exit(0)
''')
    obs = tmp_path / 'obs'
    sup = Supervisor([sys.executable, str(child)],
                     ['--obs-dir', str(obs), '--model_shards', '8'],
                     obs_dir=str(obs), backoff_s=0.05, poll_s=0.05,
                     grace_s=2.0)
    rc = sup.run()
    rec = json.load(open(obs / 'recovery.json'))
    assert rc == 0
    # Two valid classification orders: the live poll can spot the
    # tombstone before the child's exit is reaped ('peer-death:host_1')
    # or the post-mortem check reclassifies the SIGKILL
    # ('peer-death:host_1 (signal:SIGKILL)') — both are peer deaths.
    assert rec['attempts'][0]['reason'].startswith('peer-death:host_1')
    assert rec['elastic'][0]['detail'] == \
        '--model_shards 8 -> 4 (shrink the mesh)'
    assert _flag_value(_evidence(obs, 1)['argv'],
                       ('--model_shards',)) == '4'


def test_two_process_simulated_hosts_peer_death(tmp_path):
    """The 2-host simulation: the supervised child is host 0 (beating
    its control heartbeat); an INDEPENDENT host-1 process beats for a
    while and dies. Host 0's supervisor must detect the stale peer,
    kill its own (soon-to-wedge) child, shrink the mesh, and the
    restarted child completes on the smaller mesh."""
    child = tmp_path / 'child.py'
    child.write_text(r'''
import json, os, sys, time
argv = sys.argv[1:]
obs_dir = argv[argv.index('--obs-dir') + 1]
k_path = os.path.join(os.path.dirname(obs_dir.rstrip('/')), 'k.json')
k = 0
if os.path.exists(k_path):
    k = json.load(open(k_path))['k'] + 1
json.dump({'k': k}, open(k_path, 'w'))
os.makedirs(obs_dir, exist_ok=True)
json.dump({'argv': argv}, open(os.path.join(obs_dir, 'evidence.json'),
                               'w'))
cdir = os.path.join(obs_dir, 'control')
os.makedirs(cdir, exist_ok=True)

def beat(step):
    p = os.path.join(cdir, 'host_0.json')
    json.dump({'host': 0, 'pid': os.getpid(), 'time': time.time(),
               'phase': 'step', 'step': step}, open(p + '.tmp', 'w'))
    os.replace(p + '.tmp', p)

if k == 0:
    open(os.path.join(obs_dir, 'ready'), 'w').close()
    for step in range(1, 10000):   # runs until the supervisor kills us
        beat(step)
        time.sleep(0.05)
beat(1)
sys.exit(0)
''')
    host1 = tmp_path / 'host1.py'
    host1.write_text(r'''
import json, os, sys, time
cdir, beats = sys.argv[1], int(sys.argv[2])
os.makedirs(cdir, exist_ok=True)
for step in range(1, beats + 1):
    p = os.path.join(cdir, 'host_1.json')
    json.dump({'host': 1, 'pid': os.getpid(), 'time': time.time(),
               'phase': 'step', 'step': step}, open(p + '.tmp', 'w'))
    os.replace(p + '.tmp', p)
    time.sleep(0.05)
# ...and dies here, mid-"epoch": the heartbeat goes stale.
''')
    obs = tmp_path / 'obs'
    sup = Supervisor([sys.executable, str(child)],
                     ['--obs-dir', str(obs), '--model_shards', '2'],
                     obs_dir=str(obs), backoff_s=0.05, poll_s=0.05,
                     grace_s=2.0, peer_stale_s=0.6)
    result = {}

    def run():
        result['rc'] = sup.run()

    t = threading.Thread(target=run)
    t.start()
    try:
        # Wait for attempt 0's child to be up and beating...
        deadline = time.time() + 30
        while time.time() < deadline and not (
                obs / 'attempt_0' / 'ready').exists():
            time.sleep(0.02)
        assert (obs / 'attempt_0' / 'ready').exists()
        # ...then run host 1 beside it for ~0.5 s, after which it dies.
        subprocess.run(
            [sys.executable, str(host1),
             str(obs / 'attempt_0' / 'control'), '10'],
            timeout=60, check=True)
    finally:
        t.join(timeout=120)
    assert not t.is_alive()
    assert result['rc'] == 0
    rec = json.load(open(obs / 'recovery.json'))
    assert rec['outcome'] == 'completed'
    assert rec['attempts'][0]['reason'] == 'peer-death:host_1'
    assert rec['elastic'][0]['detail'] == \
        '--model_shards 2 -> 1 (shrink the mesh)'
    led = _ledger(obs)
    assert led['mesh'] == {'shards': 1}
    assert _flag_value(_evidence(obs, 1)['argv'],
                       ('--model_shards',)) == '1'


def test_no_first_heartbeat_does_not_shrink(tmp_path):
    """A child killed before its first heartbeat may just have been
    compiling slowly — permanently halving a healthy mesh for that is
    the worse error, so no-first-heartbeat restarts on the SAME mesh
    (the distributed-init wedge gets its crisp signal from the fence
    guard's rc instead)."""
    rc, rec, obs = _supervise(
        tmp_path, [{'action': 'wedge-early'}, {'action': 'ok'}],
        argv=['--model_shards', '4'], hang_deadline_s=0.3,
        first_heartbeat_s=1.0)
    assert rc == 0
    assert rec['attempts'][0]['reason'] == 'no-first-heartbeat'
    assert rec['elastic'] == []
    assert _flag_value(_evidence(obs, 1)['argv'],
                       ('--model_shards',)) == '4'


def test_own_child_staleness_is_not_peer_death(tmp_path):
    """This host's own control heartbeat going stale (a delayed write,
    an overloaded child) is the watchdog layer's business — it must not
    read as a dead PEER and shrink a healthy mesh."""
    sup = Supervisor(['true'], [], obs_dir=str(tmp_path / 'obs'),
                     host_index=0, peer_stale_s=0.5)
    cdir = str(tmp_path / 'cdir')
    os.makedirs(cdir)
    now = time.time()
    with open(os.path.join(cdir, 'host_0.json'), 'w') as f:
        json.dump({'host': 0, 'time': now - 60}, f)   # self: very stale
    with open(os.path.join(cdir, 'host_1.json'), 'w') as f:
        json.dump({'host': 1, 'time': now}, f)        # peer: fresh
    assert sup._dead_peer(cdir) is None
    # The symmetric case — the PEER stale, self fresh — still detects.
    with open(os.path.join(cdir, 'host_0.json'), 'w') as f:
        json.dump({'host': 0, 'time': now}, f)
    with open(os.path.join(cdir, 'host_1.json'), 'w') as f:
        json.dump({'host': 1, 'time': now - 60}, f)
    assert sup._dead_peer(cdir) == 'host_1'


def test_clear_control_dir_spares_current_session_files(tmp_path):
    """On a shared obs filesystem a faster host's child may have
    written THIS attempt's control files before this supervisor reaches
    the attempt: only files predating the supervisor session (a reused
    obs dir) are cleared."""
    sup = Supervisor(['true'], [], obs_dir=str(tmp_path / 'obs'))
    cdir = tmp_path / 'cdir'
    os.makedirs(cdir)
    old = cdir / 'host_1.json'
    old.write_text('{"host": 1, "time": 1}')
    os.utime(old, (time.time() - 3600, time.time() - 3600))
    fresh = cdir / 'host_0.tombstone.json'
    fresh.write_text('{"host": 0, "time": 1}')   # mtime = now
    sup._clear_control_dir(str(cdir))
    assert not old.exists()
    assert fresh.exists()


def test_follower_adopts_leader_mesh_decision(tmp_path):
    """A follower supervisor (host_index > 0) must restart on the
    LEADER's decided mesh size, not its own guess — two hosts rejoining
    with different --model_shards would wedge the first collective."""
    from dgmc_tpu.resilience.distributed_guard import (RecoveryLedger,
                                                       control_dir)
    obs = tmp_path / 'obs'
    # The leader (running elsewhere) has already decided attempt 1.
    os.makedirs(control_dir(str(obs)))
    RecoveryLedger(control_dir(str(obs)), host_index=0).decide(
        1, 'peer-death:host_2', mesh={'shards': 2})
    rc, rec, obs_dir = _supervise(
        tmp_path, [{'action': 'crash'}, {'action': 'ok'}],
        argv=['--model_shards', '8'], host_index=1, elastic=False)
    assert rc == 0
    assert any(e['event'] == 'ledger-adopt' for e in rec['events'])
    assert _flag_value(_evidence(obs_dir, 1)['argv'],
                       ('--model_shards',)) == '2'


# -- slow: the real CLI ----------------------------------------------------

#: ckpt_every 2 + the kill at epoch 4 is deliberate: checkpoint saves
#: are ASYNC, so a fault adjacent to a save races its commit (a torn
#: latest step makes the restart resume one step earlier — correct
#: behavior, but a different epoch→mesh schedule than the control run).
#: Killing two epochs after the last save keeps the resume point
#: deterministic, which is what makes the parity assertion EXACT.
SYN = ['--synthetic', '--syn_nodes_s', '48', '--syn_nodes_t', '64',
       '--syn_edges_s', '160', '--syn_edges_t', '224', '--syn_dim', '16',
       '--dim', '16', '--rnd_dim', '8', '--num_layers', '1',
       '--num_steps', '2', '--k', '5', '--phase1_epochs', '2',
       '--ckpt_every', '2', '--seed', '11']


def _run_cli(tmp_path, tag, extra, timeout=900, expect_rc=0):
    env = dict(os.environ, JAX_PLATFORMS='cpu',
               JAX_ENABLE_COMPILATION_CACHE='false')
    log = tmp_path / f'{tag}.log'
    with open(log, 'w') as fh:
        proc = subprocess.run(
            [sys.executable, '-m', 'dgmc_tpu.experiments.dbp15k']
            + SYN + extra,
            cwd=REPO, env=env, stdout=fh, stderr=subprocess.STDOUT,
            timeout=timeout)
    out = log.read_text()
    assert proc.returncode == expect_rc, (tag, proc.returncode,
                                          out[-3000:])
    return out


def _final_leaves(ckpt_dir):
    import numpy as np
    import orbax.checkpoint as ocp
    import jax
    mgr = ocp.CheckpointManager(str(ckpt_dir))
    step = mgr.latest_step()
    tree = mgr.restore(step, args=ocp.args.StandardRestore())
    mgr.close()
    return step, [np.asarray(x) for x in jax.tree.leaves(tree)]


@pytest.mark.slow
def test_peer_death_elastic_recovery_parity(tmp_path):
    """The acceptance criterion: peer-death@4 on the 8-shard mesh under
    --supervise → elastic shrink to 4 shards → resume from the epoch-2
    checkpoint RESHARDED onto the smaller mesh → final state exactly
    equal to an uninterrupted run that switched to the 4-shard mesh at
    the same epoch (same epochs on same meshes, same PRNG stream —
    determinism is positional, so parity is exact)."""
    import numpy as np
    ck_control = tmp_path / 'ck_control'
    # Control leg 1: epochs 1-2 on the 8-shard mesh (what the chaos run
    # durably completed before the peer died — the epoch-3 work it did
    # on the 8-shard mesh is discarded with the unreached checkpoint).
    _run_cli(tmp_path, 'control8',
             ['--epochs', '2', '--model_shards', '8',
              '--ckpt_dir', str(ck_control)])
    # Control leg 2: the uninterrupted shrunk-mesh run — resumes the
    # 8-shard checkpoint on the 4-shard mesh (itself exercising the
    # resharded restore) and runs epochs 3-6 without incident.
    _run_cli(tmp_path, 'control4',
             ['--epochs', '6', '--model_shards', '4',
              '--ckpt_dir', str(ck_control)])

    ck_chaos = tmp_path / 'ck_chaos'
    obs = tmp_path / 'obs'
    out = _run_cli(tmp_path, 'chaos',
                   ['--epochs', '6', '--model_shards', '8',
                    '--ckpt_dir', str(ck_chaos),
                    '--obs-dir', str(obs),
                    '--inject-fault', 'peer-death@4',
                    '--supervise', '--max-restarts', '3',
                    '--restart-backoff', '0.1'])
    assert 'firing peer-death@4' in out
    assert 'elastic-shrink' in out
    # The resume point must be the committed epoch-2 checkpoint (see
    # the SYN comment) or the parity below compares different mesh
    # schedules.
    assert 'at epoch 2.' in out

    rec = json.load(open(obs / 'recovery.json'))
    assert rec['outcome'] == 'completed'
    assert rec['restarts'] == 1
    assert rec['attempts'][0]['reason'].startswith('peer-death:host_0')
    assert rec['elastic'][0]['detail'] == \
        '--model_shards 8 -> 4 (shrink the mesh)'
    led = json.load(open(obs / 'control' / 'ledger.json'))
    assert led['mesh'] == {'shards': 4}

    step_a, leaves_a = _final_leaves(ck_control)
    step_b, leaves_b = _final_leaves(ck_chaos)
    assert step_a == step_b == 6
    assert len(leaves_a) == len(leaves_b)
    for x, y in zip(leaves_a, leaves_b):
        np.testing.assert_array_equal(x, y)

    # The elastic event renders through obs.report and GATES through
    # obs.diff: a candidate that shrank vs a baseline that did not is a
    # regression (scaling numbers changed out from under the metrics).
    rep = subprocess.run(
        [sys.executable, '-m', 'dgmc_tpu.obs.report', str(obs)],
        cwd=REPO, capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS='cpu'), timeout=120)
    assert rep.returncode == 0, rep.stderr[-2000:]
    assert 'elastic shrink' in rep.stdout


@pytest.mark.slow
def test_fence_deadline_converts_stall_into_forensics(tmp_path):
    """collective-stall@2 inside the epoch fence, --fence-deadline 3:
    instead of hanging to rc:124, the run exits FENCE_TIMEOUT_RC with a
    hang_report.json naming the fence phase/step, and obs.aggregate
    attributes the hung host to its last completed fence/phase."""
    obs = tmp_path / 'obs'
    out = _run_cli(
        tmp_path, 'stall',
        ['--epochs', '3', '--phase1_epochs', '1', '--model_shards', '8',
         '--obs-dir', str(obs), '--fence-deadline', '3',
         '--inject-fault', 'collective-stall@2:60'],
        expect_rc=FENCE_TIMEOUT_RC)
    assert 'firing collective-stall@2 inside the step-2 fence' in out
    rep = json.load(open(obs / 'hang_report.json'))
    assert rep['reason'].startswith('fence-deadline')
    assert rep['fence'] == {'phase': 'epoch-fence', 'step': 2}

    agg = subprocess.run(
        [sys.executable, '-m', 'dgmc_tpu.obs.aggregate', str(obs),
         '--json'],
        cwd=REPO, capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS='cpu'), timeout=120)
    assert agg.returncode == 0, agg.stderr[-2000:]
    summary = json.loads(agg.stdout)
    assert summary['hung_hosts'] == ['host_0']
    att = summary['hang_attribution']['host_0']
    assert att['reason'].startswith('fence-deadline')
    assert att['in_flight'] == {'phase': 'fence', 'name': 'epoch-fence'}
    # The control-plane heartbeat pins the last thing this host was
    # doing (the epoch it entered before wedging in the fence).
    assert att['last_heartbeat']['step'] == 2
