"""datasets/download.py retry behavior, driven by the deterministic
transient-download fault: transient failures back off and retry, the
budget is finite with a terminal actionable error, and permanent
failures (4xx, bad paths) never burn retries.
"""

import urllib.error

import pytest

from dgmc_tpu.datasets import download
from dgmc_tpu.resilience import faults


@pytest.fixture(autouse=True)
def _no_sleep_no_leftover_faults(monkeypatch):
    monkeypatch.setattr('time.sleep', lambda s: None)
    faults.arm_download_faults(0)
    yield
    faults.arm_download_faults(0)


@pytest.fixture
def src(tmp_path):
    p = tmp_path / 'payload.bin'
    p.write_bytes(b'dgmc' * 100)
    return p


def test_fetch_retries_past_transient_faults(tmp_path, src, capsys):
    faults.arm_download_faults(2)
    dest = tmp_path / 'out.bin'
    got = download.fetch(src.as_uri(), str(dest), retries=4,
                         backoff_s=0.01)
    assert got == str(dest)
    assert dest.read_bytes() == src.read_bytes()
    assert faults.download_faults_remaining() == 0
    err = capsys.readouterr().err
    assert err.count('retrying in') == 2


def test_fetch_exhausted_budget_raises_terminal(tmp_path, src):
    faults.arm_download_faults(10)
    dest = tmp_path / 'out.bin'
    with pytest.raises(RuntimeError) as e:
        download.fetch(src.as_uri(), str(dest), retries=2, backoff_s=0.01)
    msg = str(e.value)
    assert 'after 3 attempt(s)' in msg
    assert 'fetch it manually' in msg
    assert not dest.exists()
    assert not dest.with_suffix('.bin.part').exists()


def test_fetch_permanent_failure_not_retried(tmp_path, monkeypatch):
    calls = []

    def fake_urlopen(url, timeout=None):
        calls.append(url)
        raise urllib.error.HTTPError(url, 404, 'Not Found', {}, None)

    monkeypatch.setattr(download.urllib.request, 'urlopen', fake_urlopen)
    with pytest.raises(RuntimeError) as e:
        download.fetch('http://example.invalid/x.zip',
                       str(tmp_path / 'x.zip'), retries=5, backoff_s=0.01)
    assert len(calls) == 1, 'a 404 must not be retried'
    assert 'after 1 attempt(s)' in str(e.value)


def test_fetch_rate_limit_is_transient(tmp_path, src, monkeypatch):
    """429 is the server saying "later", not "never": it retries."""
    calls = []
    real_urlopen = download.urllib.request.urlopen

    def flaky_urlopen(url, timeout=None):
        calls.append(url)
        if len(calls) < 3:
            raise urllib.error.HTTPError(url, 429, 'Too Many Requests',
                                         {}, None)
        return real_urlopen(url, timeout=timeout)

    monkeypatch.setattr(download.urllib.request, 'urlopen', flaky_urlopen)
    dest = tmp_path / 'out.bin'
    download.fetch(src.as_uri(), str(dest), retries=4, backoff_s=0.01)
    assert len(calls) == 3
    assert dest.read_bytes() == src.read_bytes()


def test_env_var_arms_download_faults():
    """Subprocess tests arm the fault through the environment; the
    module-level budget reads it at import. Pin the documented name in a
    fresh interpreter (reloading the module in-process would rebind the
    exception classes other tests hold references to)."""
    import os
    import subprocess
    import sys
    out = subprocess.run(
        [sys.executable, '-c',
         'from dgmc_tpu.resilience import faults; '
         'print(faults.download_faults_remaining())'],
        env=dict(os.environ, DGMC_TPU_FAULT_DOWNLOADS='2',
                 JAX_PLATFORMS='cpu'),
        capture_output=True, text=True, timeout=120,
        cwd=os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))))
    assert out.returncode == 0, out.stderr[-2000:]
    assert out.stdout.strip() == '2'
