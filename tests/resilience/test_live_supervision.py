"""Endpoint-aware supervision: a child that advertises a live
``/healthz`` port in its heartbeat is monitored through the endpoint —
a 503 kills it as ``healthz-stale`` even while its heartbeat FILE stays
fresh, and a healthy endpoint keeps it alive even when the file is
stale (write lag must not kill a provably-live child). File heartbeats
remain the fallback when the scrape fails. jax-free on both sides,
like test_supervisor.py."""

import json
import sys

from dgmc_tpu.resilience.supervisor import Supervisor

#: Toy child serving a real /healthz with a scripted verdict while
#: keeping (or aging) its heartbeat FILE independently — the two
#: vantage points the supervisor must rank correctly. Attempt index
#: persists in a counter file; attempt >= 1 exits clean so kill tests
#: end in completion.
CHILD = r'''
import http.server, json, os, sys, threading, time
counter_path, mode = sys.argv[1], sys.argv[2]
argv = sys.argv[3:]
obs_dir = None
for i, tok in enumerate(argv):
    if tok in ('--obs-dir', '--obs_dir'):
        obs_dir = argv[i + 1]
k = 0
if os.path.exists(counter_path):
    k = json.load(open(counter_path))['attempt'] + 1
json.dump({'attempt': k}, open(counter_path, 'w'))
if k >= 1:
    sys.exit(0)

healthy = (mode == 'healthy')


class H(http.server.BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass

    def do_GET(self):
        if mode == 'erroring':
            # An errored handler: 500 with no 'healthy' verdict —
            # must read as a FAILED scrape, not as "stale".
            body = json.dumps({'error': 'boom'}).encode()
            self.send_response(500)
        else:
            body = json.dumps({'healthy': healthy}).encode()
            self.send_response(200 if healthy else 503)
        self.send_header('Content-Type', 'application/json')
        self.send_header('Content-Length', str(len(body)))
        self.end_headers()
        self.wfile.write(body)


srv = http.server.ThreadingHTTPServer(('127.0.0.1', 0), H)
threading.Thread(target=srv.serve_forever, daemon=True).start()
port = srv.server_address[1]
os.makedirs(obs_dir, exist_ok=True)
hb = os.path.join(obs_dir, 'heartbeat.json')


def beat(t):
    json.dump({'time': t, 'pid': os.getpid(), 'port': port},
              open(hb, 'w'))


if mode == 'unhealthy':
    # FRESH file heartbeats forever: only the endpoint says stale —
    # the kill must be attributed to /healthz, not the file.
    end = time.time() + 60
    while time.time() < end:
        beat(time.time())
        time.sleep(0.05)
    sys.exit(1)
elif mode == 'erroring':
    # 500-answering endpoint + FRESH file heartbeats: the failed
    # scrape must fall back to the (healthy) file — no kill; the
    # child completes on its own.
    end = time.time() + 1.2
    while time.time() < end:
        beat(time.time())
        time.sleep(0.05)
    sys.exit(0)
elif mode == 'healthy':
    # Endpoint healthy, file heartbeat ANCIENT: the live verdict must
    # outrank the stale file, and the run completes untouched.
    beat(time.time() - 3600)
    time.sleep(1.2)
    sys.exit(0)
elif mode == 'dead-port':
    # Advertises a port nothing listens on: scrape fails -> file
    # fallback; the file is stale -> heartbeat-stale, as before.
    srv.shutdown()
    srv.server_close()
    json.dump({'time': time.time() - 3600, 'pid': os.getpid(),
               'port': port}, open(hb, 'w'))
    time.sleep(60)
'''


def _supervise(tmp_path, mode, **kw):
    child = tmp_path / 'child.py'
    child.write_text(CHILD)
    obs = tmp_path / 'obs'
    sup = Supervisor(
        [sys.executable, str(child), str(tmp_path / 'counter.json'),
         mode],
        ['--obs-dir', str(obs)],
        obs_dir=str(obs), max_restarts=3, backoff_s=0.05,
        grace_s=2.0, poll_s=0.05, hang_deadline_s=0.3, **kw)
    rc = sup.run()
    recovery = json.load(open(obs / 'recovery.json'))
    return rc, recovery


def test_healthz_503_kills_despite_fresh_file_heartbeat(tmp_path):
    rc, rec = _supervise(tmp_path, 'unhealthy')
    assert rc == 0
    assert rec['outcome'] == 'completed'
    assert rec['attempts'][0]['reason'] == 'healthz-stale'
    assert rec['attempts'][1]['reason'] == 'completed'


def test_healthy_endpoint_outranks_stale_file(tmp_path):
    """heartbeat.json is an hour old, but /healthz answers 200: the
    child must NOT be killed (write lag is not a hang when the plane
    itself answers healthy) and completes on attempt 0."""
    rc, rec = _supervise(tmp_path, 'healthy')
    assert rc == 0
    assert rec['outcome'] == 'completed'
    assert rec['restarts'] == 0
    assert rec['attempts'][0]['reason'] == 'completed'


def test_500_endpoint_is_a_failed_scrape_not_a_stale_child(tmp_path):
    """An erroring health handler (500, no 'healthy' key) must NOT be
    read as a stale verdict: the supervisor falls back to the fresh
    file heartbeat and the healthy child completes untouched."""
    rc, rec = _supervise(tmp_path, 'erroring')
    assert rc == 0
    assert rec['outcome'] == 'completed'
    assert rec['restarts'] == 0
    assert rec['attempts'][0]['reason'] == 'completed'


def test_unreachable_port_falls_back_to_file_heartbeat(tmp_path):
    rc, rec = _supervise(tmp_path, 'dead-port')
    assert rc == 0
    assert rec['outcome'] == 'completed'
    assert rec['attempts'][0]['reason'] == 'heartbeat-stale'


def test_healthz_stale_is_a_distributed_failure(tmp_path):
    """The elastic classifier treats the endpoint verdict like the
    file verdict: a wedged collective looks identical through both."""
    sup = Supervisor([sys.executable, '-c', 'pass'], [],
                     obs_dir=str(tmp_path / 'obs'))
    assert sup._is_distributed_failure('healthz-stale')
    assert sup._is_distributed_failure('heartbeat-stale')
    assert not sup._is_distributed_failure('exit:3')
