"""Restore onto a DIFFERENT mesh than the checkpoint was saved under —
the elastic mesh-shrink rung's load-bearing half. Pins the two fixed
failure modes:

- a restore target with no shardings (host numpy state, what
  ``resume_or_init`` passes) used to make orbax read the sharding
  recorded in the checkpoint; after a topology shrink that sharding
  names dead devices and the placement error masqueraded as
  ``CheckpointCorruptError`` ("every checkpoint failed to restore");
- a restored-but-single-device-committed state fed to a mesh-
  constrained step crashed with "incompatible devices" —
  ``resume_or_init(mesh=...)`` now re-derives target shardings on the
  CURRENT mesh so the state deserializes directly onto it.

The cross-topology cases (8 devices at save, genuinely only 4 at
restore) run in subprocesses with their own ``XLA_FLAGS``.
"""

import os
import subprocess
import sys

import jax
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dgmc_tpu.train import Checkpointer, create_train_state, \
    resume_or_init

from tests.train.test_steps import tiny_loader, tiny_model

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]).reshape(n, 1),
                ('data', 'model'))


def _state(seed=0):
    model = tiny_model()
    batch = next(iter(tiny_loader()))
    return create_train_state(model, jax.random.key(seed), batch)


def test_host_numpy_target_restores_values(tmp_path):
    """A shardingless (host numpy) restore target comes back as host
    numpy — not via the checkpoint's recorded placement."""
    state = _state()
    ckpt = Checkpointer(tmp_path / 'ck')
    ckpt.save(1, state, wait=True)
    ckpt.close()

    target = jax.tree.map(
        lambda x: np.asarray(x) if hasattr(x, 'shape') else x, state)
    ckpt = Checkpointer(tmp_path / 'ck')
    restored = ckpt.restore(target)
    assert ckpt.restored_step == 1
    for got, want in zip(jax.tree.leaves(restored),
                         jax.tree.leaves(state)):
        if np.ndim(want):
            # Non-scalar leaves come back as host numpy, never as
            # device arrays placed by the checkpoint's recorded
            # sharding (scalars may deserialize as Python numbers).
            assert isinstance(got, np.ndarray)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    ckpt.close()


def test_resume_or_init_places_state_on_mesh(tmp_path):
    """``resume_or_init(mesh=...)`` restores every leaf onto the given
    mesh (replicated without rules) — including a mesh SMALLER than the
    checkpoint's: the committed-to-device-0 vs mesh-constraint crash is
    gone because the state never bounces through one device."""
    state = _state()
    mesh8 = _mesh(8)
    placed = jax.device_put(state, NamedSharding(mesh8, P()))
    ckpt = Checkpointer(tmp_path / 'ck')
    ckpt.save(2, placed, wait=True)
    ckpt.close()

    mesh4 = _mesh(4)
    ckpt, restored, start = resume_or_init(
        str(tmp_path / 'ck'), _state(seed=9), mesh=mesh4)
    assert start == 3
    for got, want in zip(jax.tree.leaves(restored),
                         jax.tree.leaves(placed)):
        if not hasattr(got, 'sharding'):
            continue
        assert got.sharding.mesh.devices.size == 4, got.sharding
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    ckpt.close()


def test_resume_or_init_mesh_with_rules(tmp_path):
    """The partition-rule path: the restore target's shardings come
    from the declarative config on the CURRENT mesh."""
    from dgmc_tpu.parallel.rules import streamed_rules
    state = _state()
    ckpt = Checkpointer(tmp_path / 'ck')
    ckpt.save(1, state, wait=True)
    ckpt.close()

    rules = streamed_rules()
    ckpt, restored, start = resume_or_init(
        str(tmp_path / 'ck'), _state(seed=9), mesh=_mesh(2), rules=rules)
    assert start == 2
    leaf = jax.tree.leaves(restored.params)[0]
    assert leaf.sharding.mesh.devices.size == 2
    ckpt.close()


def test_fresh_start_is_still_placed_on_mesh(tmp_path):
    """No checkpoint yet: the initial state still lands on the mesh, so
    the first epoch and a resumed epoch see identically-placed state."""
    _ckpt, state, start = resume_or_init(
        str(tmp_path / 'empty_ck'), _state(), mesh=_mesh(4))
    assert start == 1
    leaf = jax.tree.leaves(state.params)[0]
    assert leaf.sharding.mesh.devices.size == 4


_SAVE8 = r'''
import os, sys
os.environ['XLA_FLAGS'] = '--xla_force_host_platform_device_count=8'
os.environ['JAX_PLATFORMS'] = 'cpu'
import jax, numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from dgmc_tpu.train.checkpoint import Checkpointer
mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ('data', 'model'))
state = {
    'w': jax.device_put(jnp.arange(64.0).reshape(8, 8),
                        NamedSharding(mesh, P('data', None))),
    'b': jax.device_put(jnp.ones((8,)) * 5, NamedSharding(mesh, P())),
    'count': jnp.asarray(3),
}
ck = Checkpointer(sys.argv[1])
ck.save(5, state, wait=True)
ck.close()
print('SAVED8 ok')
'''

_RESTORE4 = r'''
import os, sys, json
os.environ['XLA_FLAGS'] = '--xla_force_host_platform_device_count=4'
os.environ['JAX_PLATFORMS'] = 'cpu'
import jax, numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from dgmc_tpu.train.checkpoint import Checkpointer
assert len(jax.devices()) == 4

# (a) host numpy target: must deserialize to host, NOT the dead saved
# topology (this raised CheckpointCorruptError before the fix).
host_target = {'w': np.zeros((8, 8), np.float32),
               'b': np.zeros((8,), np.float32),
               'count': np.asarray(0)}
ck = Checkpointer(sys.argv[1])
got = ck.restore(host_target)
assert ck.restored_step == 5, ck.restored_step
assert isinstance(got['w'], np.ndarray), type(got['w'])
assert float(got['w'][3, 3]) == 27.0 and int(got['count']) == 3
ck.close()

# (b) mesh target: leaves land resharded on the 4-device mesh.
mesh = Mesh(np.array(jax.devices()).reshape(2, 2), ('data', 'model'))
target = {
    'w': jax.device_put(jnp.zeros((8, 8)),
                        NamedSharding(mesh, P('data', None))),
    'b': jax.device_put(jnp.zeros((8,)), NamedSharding(mesh, P())),
    'count': jnp.asarray(0),
}
ck = Checkpointer(sys.argv[1])
got = ck.restore(target)
assert got['w'].sharding.mesh.devices.size == 4
assert float(got['w'][3, 3]) == 27.0 and float(got['b'][0]) == 5.0
ck.close()
print('RESTORE4 ok')
'''


@pytest.mark.slow
def test_restore_on_genuinely_shrunk_topology(tmp_path):
    """8 devices at save, 4 at restore (separate processes, separate
    XLA_FLAGS): both the host-target and the mesh-target restores must
    succeed — this is the topology change an elastic restart survives."""
    ck_dir = str(tmp_path / 'ck')
    env = {k: v for k, v in os.environ.items() if k != 'XLA_FLAGS'}
    env['JAX_ENABLE_COMPILATION_CACHE'] = 'false'
    for code, tag in ((_SAVE8, 'SAVED8'), (_RESTORE4, 'RESTORE4')):
        proc = subprocess.run([sys.executable, '-c', code, ck_dir],
                              cwd=REPO, env=env, timeout=600,
                              capture_output=True, text=True)
        assert proc.returncode == 0, (tag, proc.stderr[-3000:])
        assert f'{tag} ok' in proc.stdout
