"""Flight recorder end to end through the real CLI: an injected stall
under the supervisor must leave a ``flight.json`` whose last recorded
span matches the span ``hang_report.json`` names — the acceptance
criterion of the live-telemetry plane. Slow: each test is a full jax
bring-up in a child process (same harness as test_elastic.py)."""

import json
import os
import subprocess
import sys

import pytest

from dgmc_tpu.resilience.distributed_guard import FENCE_TIMEOUT_RC

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SYN = ['--synthetic', '--syn_nodes_s', '48', '--syn_nodes_t', '64',
       '--syn_edges_s', '160', '--syn_edges_t', '224', '--syn_dim', '16',
       '--dim', '16', '--rnd_dim', '8', '--num_layers', '1',
       '--num_steps', '2', '--k', '5', '--phase1_epochs', '1',
       '--epochs', '3', '--seed', '11']


def _run_cli(tmp_path, tag, extra, timeout=900, expect_rc=0):
    env = dict(os.environ, JAX_PLATFORMS='cpu',
               JAX_ENABLE_COMPILATION_CACHE='false')
    log = tmp_path / f'{tag}.log'
    with open(log, 'w') as fh:
        proc = subprocess.run(
            [sys.executable, '-m', 'dgmc_tpu.experiments.dbp15k']
            + SYN + extra,
            cwd=REPO, env=env, stdout=fh, stderr=subprocess.STDOUT,
            timeout=timeout)
    out = log.read_text()
    assert proc.returncode == expect_rc, (tag, proc.returncode,
                                          out[-3000:])
    return out


def _report(obs):
    rep = subprocess.run(
        [sys.executable, '-m', 'dgmc_tpu.obs.report', str(obs)],
        cwd=REPO, capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS='cpu'), timeout=120)
    assert rep.returncode == 0, rep.stderr[-2000:]
    return rep.stdout


@pytest.mark.slow
def test_collective_stall_flight_matches_hang_report_fence(tmp_path):
    """collective-stall@2 inside the epoch fence under --supervise:
    the fence guard exits rc 67 AND dumps flight.json, whose last
    recorded span is the very fence hang_report.json's in-flight span
    names — the trailing-context + stack-dump pair."""
    obs = tmp_path / 'obs'
    out = _run_cli(
        tmp_path, 'stall',
        ['--obs-dir', str(obs),
         '--watchdog-deadline', '120', '--fence-deadline', '3',
         '--inject-fault', 'collective-stall@2:90',
         '--supervise', '--max-restarts', '0',
         '--restart-backoff', '0.1'],
        expect_rc=FENCE_TIMEOUT_RC)
    assert 'firing collective-stall@2 inside the step-2 fence' in out

    attempt = obs / 'attempt_0'
    hang = json.load(open(attempt / 'hang_report.json'))
    assert hang['reason'].startswith('fence-deadline')
    assert hang['in_flight']['phase'] == 'fence'

    flight = json.load(open(attempt / 'flight.json'))
    assert flight['reason'].startswith('fence-deadline')
    spans = [e for e in flight['events']
             if str(e.get('kind', '')).startswith('span')]
    last = spans[-1]
    # The flight's last recorded span IS the wedged fence: an
    # un-exited span-start whose name carries the fence phase@step
    # hang_report attributes the stall to.
    assert last['kind'] == 'span-start'
    assert last['phase'] == hang['in_flight']['phase'] == 'fence'
    assert last['name'] == (f"{hang['fence']['phase']}"
                            f"@{hang['fence']['step']}")

    rec = json.load(open(obs / 'recovery.json'))
    assert rec['attempts'][0]['reason'] == f'exit:{FENCE_TIMEOUT_RC}'

    # obs.report renders the flight timeline for the supervised root.
    text = _report(obs)
    assert 'flight recorder' in text
    assert 'fence-deadline' in text


@pytest.mark.slow
def test_host_stall_flight_matches_hang_report_last_span(tmp_path):
    """Plain stall@2 (a host-side wedge between steps) under the
    supervisor: the watchdog deadline dumps hang_report + flight; the
    flight's last completed span equals hang_report's last_completed,
    and the supervisor kills on the hang report."""
    obs = tmp_path / 'obs'
    _run_cli(
        tmp_path, 'hoststall',
        ['--obs-dir', str(obs),
         '--watchdog-deadline', '30',
         '--inject-fault', 'stall@2:600',
         '--supervise', '--max-restarts', '0',
         '--restart-backoff', '0.1'],
        expect_rc=1)

    attempt = obs / 'attempt_0'
    hang = json.load(open(attempt / 'hang_report.json'))
    # The watchdog dumps on the DEADLINE first (what the supervisor
    # keys its kill on); the supervisor's SIGTERM then re-dumps via
    # the signal path, replacing the file — both spellings are the
    # same stall, and which one survives is a race we don't pin.
    assert hang['reason'].startswith(('deadline', 'signal:'))
    last_completed = hang['last_completed']
    assert last_completed['phase'] == 'step'

    flight = json.load(open(attempt / 'flight.json'))
    assert flight['reason'].startswith(('deadline', 'signal:'))
    ends = [e for e in flight['events'] if e.get('kind') == 'span-end'
            and e.get('phase') == 'step']
    assert ends, flight['events']
    assert ends[-1]['step'] == last_completed['name']

    rec = json.load(open(obs / 'recovery.json'))
    assert rec['outcome'] == 'gave-up'
    assert rec['attempts'][0]['reason'] == 'hang-report'
