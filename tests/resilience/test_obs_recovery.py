"""The recovery timeline through the obs toolchain: a supervised obs
root (recovery.json + attempt_<k>/ subdirs) must load as its final
attempt, summarize/render the timeline, and diff-gate on restart
regressions — all from artifacts alone, no live run.
"""

import json
import os

import pytest

from dgmc_tpu.obs.diff import diff_runs
from dgmc_tpu.obs.report import load_run, render, summarize


def _write_attempt(root, k, steps=3, hang=False):
    d = os.path.join(root, f'attempt_{k}')
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, 'metrics.jsonl'), 'w') as f:
        for s in range(1, steps + 1):
            f.write(json.dumps({'step': s, 'loss': 1.0 / s}) + '\n')
    with open(os.path.join(d, 'timings.json'), 'w') as f:
        json.dump({'steps': {'count': steps, 'mean_s': 0.1,
                             'p50_s': 0.1, 'p95_s': 0.12, 'max_s': 0.2,
                             'total_s': 0.1 * steps},
                   'compiles': {'events': [], 'total_s': 0.0},
                   'wall_s': 1.0}, f)
    if hang:
        with open(os.path.join(d, 'hang_report.json'), 'w') as f:
            json.dump({'reason': 'deadline: no event for 5.0s'}, f)
    return d


def _write_recovery(root, restarts, outcome='completed', degradations=(),
                    elastic=()):
    os.makedirs(root, exist_ok=True)
    attempts = [
        {'attempt': k, 'reason': 'signal:SIGKILL', 'rc': -9,
         'steps_completed': 2, 'start_time': 100.0 + 10 * k,
         'end_time': 105.0 + 10 * k}
        for k in range(restarts)]
    attempts.append({'attempt': restarts, 'reason': 'completed', 'rc': 0,
                     'steps_completed': 3, 'start_time': 200.0,
                     'end_time': 210.0})
    with open(os.path.join(root, 'recovery.json'), 'w') as f:
        json.dump({'outcome': outcome, 'restarts': restarts,
                   'degradations': [{'rung': r, 'attempt': 1,
                                     'detail': r} for r in degradations],
                   'elastic': [{'attempt': 0,
                                'reason': 'peer-death:host_1',
                                'detail': d, 'mesh_after': 4}
                               for d in elastic],
                   'attempts': attempts, 'events': []}, f)


@pytest.fixture
def supervised_root(tmp_path):
    root = str(tmp_path / 'obs')
    _write_recovery(root, restarts=1)
    _write_attempt(root, 0, hang=True)   # the killed attempt
    _write_attempt(root, 1)              # the clean resume
    return root


def test_load_run_binds_last_attempt(supervised_root):
    run = load_run(supervised_root)
    assert run['attempts'] == 2
    assert run['recovery']['restarts'] == 1
    # The final attempt is the run's outcome: its timings, and NOT the
    # killed attempt's hang report (a recovered run must not diff as
    # hung).
    assert run['timings']['steps']['count'] == 3
    assert run['hang'] is None


def test_summarize_and_render_timeline(supervised_root):
    s = summarize(load_run(supervised_root))
    assert s['recovery']['outcome'] == 'completed'
    assert s['recovery']['restarts'] == 1
    assert [a['reason'] for a in s['recovery']['attempts']] == \
        ['signal:SIGKILL', 'completed']
    text = render(load_run(supervised_root))
    assert 'recovery timeline' in text
    assert 'signal:SIGKILL' in text


def test_diff_gates_on_extra_restarts(tmp_path, supervised_root):
    base_root = str(tmp_path / 'base')
    _write_recovery(base_root, restarts=0)
    _write_attempt(base_root, 0)
    base = summarize(load_run(base_root))
    cand = summarize(load_run(supervised_root))

    # Default threshold 0: one new restart is a regression.
    rows, regs = diff_runs(base, cand)
    row = next(r for r in rows if r['metric'] == 'restarts')
    assert row['status'] == 'REGRESSION' and row in regs
    # Identical runs: clean.
    rows, regs = diff_runs(cand, cand)
    row = next(r for r in rows if r['metric'] == 'restarts')
    assert row['status'] == 'ok' and not regs
    # Slack of 1 restart: allowed.
    rows, _regs = diff_runs(base, cand, thresholds={'restarts': 1})
    row = next(r for r in rows if r['metric'] == 'restarts')
    assert row['status'] == 'ok'


def test_diff_gates_on_elastic_shrink(tmp_path):
    """A candidate whose supervisor shrank the mesh survived on fewer
    devices than the run asked for — every scaling number changed out
    from under the metrics, so the diff must fail even when the restart
    slack would have allowed the restart itself."""
    base_root = str(tmp_path / 'base')
    _write_recovery(base_root, restarts=1)
    _write_attempt(base_root, 0)
    _write_attempt(base_root, 1)
    cand_root = str(tmp_path / 'cand')
    _write_recovery(cand_root, restarts=1,
                    elastic=['--model_shards 8 -> 4 (shrink the mesh)'])
    _write_attempt(cand_root, 0)
    _write_attempt(cand_root, 1)
    base = summarize(load_run(base_root))
    cand = summarize(load_run(cand_root))

    rows, regs = diff_runs(base, cand, thresholds={'restarts': 100})
    row = next(r for r in rows if r['metric'] == 'elastic_shrinks')
    assert row['status'] == 'REGRESSION' and row in regs
    assert '--model_shards 8 -> 4' in row['note']
    # Equal shrink histories (e.g. both runs re-ran the same recovery
    # scenario): clean.
    rows, regs = diff_runs(cand, cand, thresholds={'restarts': 100})
    row = next(r for r in rows if r['metric'] == 'elastic_shrinks')
    assert row['status'] == 'ok' and not regs
    # A baseline that shrank against a candidate that did not is the
    # fix, not a regression.
    rows, regs = diff_runs(cand, base, thresholds={'restarts': 100})
    row = next(r for r in rows if r['metric'] == 'elastic_shrinks')
    assert row['status'] == 'ok' and not regs


def test_elastic_events_render_in_report(tmp_path):
    root = str(tmp_path / 'obs')
    _write_recovery(root, restarts=1,
                    elastic=['--row_shards 8 -> 4 (shrink the mesh)'])
    _write_attempt(root, 0)
    _write_attempt(root, 1)
    s = summarize(load_run(root))
    assert [e['detail'] for e in s['recovery']['elastic']] == \
        ['--row_shards 8 -> 4 (shrink the mesh)']
    text = render(load_run(root))
    assert 'elastic shrink' in text
    assert '--row_shards 8 -> 4' in text


def test_diff_gave_up_fails_unconditionally(tmp_path):
    root_a = str(tmp_path / 'a')
    _write_recovery(root_a, restarts=0)
    _write_attempt(root_a, 0)
    root_b = str(tmp_path / 'b')
    _write_recovery(root_b, restarts=5, outcome='gave-up')
    _write_attempt(root_b, 0)
    rows, regs = diff_runs(summarize(load_run(root_a)),
                           summarize(load_run(root_b)),
                           thresholds={'restarts': 100})
    rec = next(r for r in rows if r['metric'] == 'recovery')
    assert rec['status'] == 'REGRESSION' and rec in regs


def test_unsupervised_candidate_skips_gate(tmp_path):
    root_a = str(tmp_path / 'a')
    _write_recovery(root_a, restarts=2)
    _write_attempt(root_a, 0)
    root_b = str(tmp_path / 'b')
    _write_attempt(root_b, 0)
    os.rename(os.path.join(root_b, 'attempt_0'),
              os.path.join(root_b, 'solo'))
    # root_b: a plain unsupervised run dir.
    for name in os.listdir(os.path.join(root_b, 'solo')):
        os.rename(os.path.join(root_b, 'solo', name),
                  os.path.join(root_b, name))
    rows, _regs = diff_runs(summarize(load_run(root_a)),
                            summarize(load_run(root_b)))
    row = next(r for r in rows if r['metric'] == 'restarts')
    assert row['status'] == 'skipped'
