"""The distributed control plane in isolation, jax-free on both sides:
heartbeat channels (peer-death staleness, tombstones, stragglers), the
host-0 recovery ledger (leadership, follower wait), and the fence guard
(deadline miss → hang_report naming the missing host/phase; clean exit
→ no report; exit path → FENCE_TIMEOUT_RC in a subprocess).
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from dgmc_tpu.resilience.distributed_guard import (
    FENCE_TIMEOUT_RC, FenceGuard, HostChannel, LedgerError,
    RecoveryLedger, control_dir, control_root, read_heartbeats,
    read_tombstones, write_tombstone)

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


# -- HostChannel -----------------------------------------------------------

def test_channel_beat_and_peer_roundtrip(tmp_path):
    obs = str(tmp_path / 'obs')
    a = HostChannel(obs, host_index=0, num_hosts=2)
    b = HostChannel(obs, host_index=1, num_hosts=2)
    a.beat('epoch', step=3)
    b.beat('epoch', step=2)
    peers = a.peers()
    assert sorted(peers) == [0, 1]
    assert peers[0]['phase'] == 'epoch' and peers[0]['step'] == 3
    assert peers[1]['step'] == 2
    assert peers[1]['mesh'] == {'hosts': 2}
    assert peers[1]['pid'] == os.getpid()


def test_channel_record_fence_lands_in_heartbeat(tmp_path):
    obs = str(tmp_path / 'obs')
    a = HostChannel(obs, host_index=0)
    a.record_fence('epoch-fence', 5)
    rec = a.peers()[0]
    assert rec['last_fence']['phase'] == 'epoch-fence'
    assert rec['last_fence']['step'] == 5
    assert rec['step'] == 5


def test_dead_peer_by_staleness_and_tombstone(tmp_path):
    obs = str(tmp_path / 'obs')
    a = HostChannel(obs, host_index=0, num_hosts=3)
    b = HostChannel(obs, host_index=1, num_hosts=3)
    a.beat('epoch', 1)
    b.beat('epoch', 1)
    # Nobody is stale yet.
    assert a.dead_peers(stale_s=30.0) == {}
    # Host 1's heartbeat is old news from the future's point of view.
    dead = a.dead_peers(stale_s=0.5, now=time.time() + 10)
    assert 1 in dead and dead[1]['stale_s'] > 0.5
    # A host that never wrote (host 2) is absent, NOT dead.
    assert 2 not in dead
    # Tombstones are definitive, no staleness argument needed.
    write_tombstone(a.dir, 2, step=4)
    dead = a.dead_peers(stale_s=30.0)
    assert 2 in dead and dead[2]['step'] == 4
    assert read_tombstones(a.dir)[2]['reason'] == 'peer-death'


def test_straggler_detection(tmp_path):
    obs = str(tmp_path / 'obs')
    a = HostChannel(obs, host_index=0)
    b = HostChannel(obs, host_index=1)
    a.beat('epoch', step=10)
    b.beat('epoch', step=7)
    lag = a.stragglers(behind_steps=2)
    assert list(lag) == [1] and lag[1]['behind'] == 3
    # Within the allowance: no straggler.
    b.beat('epoch', step=9)
    assert a.stragglers(behind_steps=2) == {}
    # A single host can't lag itself.
    solo = HostChannel(str(tmp_path / 'solo'), host_index=0)
    solo.beat('epoch', 1)
    assert solo.stragglers() == {}


def test_refresher_thread_keeps_heartbeat_fresh_until_close(tmp_path):
    obs = str(tmp_path / 'obs')
    ch = HostChannel(obs, host_index=0, interval_s=0.05)
    with ch:
        ch.beat('epoch', 1)
        t0 = ch.peers()[0]['time']
        time.sleep(0.3)
        assert ch.peers()[0]['time'] > t0  # refreshed without a beat
    t1 = ch.peers()[0]['time']
    time.sleep(0.2)
    assert ch.peers()[0]['time'] == t1    # closed: goes stale


def test_coord_partition_suppresses_writes(tmp_path):
    """Once the coord-partition fault fires, the host stops writing —
    it LOOKS dead to its peers while still running."""
    from dgmc_tpu.resilience.faults import FaultPlan
    obs = str(tmp_path / 'obs')
    plan = FaultPlan(['coord-partition@2'])
    ch = HostChannel(obs, host_index=0, fault_plan=plan)
    ch.beat('epoch', 1)
    t0 = ch.peers()[0]['time']
    plan.before_step(2)
    assert plan.coord_partitioned
    ch.beat('epoch', 2)
    rec = ch.peers()[0]
    assert rec['time'] == t0 and rec['step'] == 1  # write suppressed


def test_control_root_strips_attempt_suffix(tmp_path):
    root = str(tmp_path / 'obs')
    assert control_root(root) == control_dir(root)
    assert control_root(os.path.join(root, 'attempt_3')) == \
        control_dir(root)


def test_read_heartbeats_ignores_junk(tmp_path):
    cdir = tmp_path / 'control'
    os.makedirs(cdir)
    (cdir / 'host_0.json').write_text('{"host": 0, "time": 1}')
    (cdir / 'host_x.json').write_text('{}')          # non-numeric
    (cdir / 'host_1.json').write_text('{not json')   # torn write
    (cdir / 'ledger.json').write_text('{}')          # not a heartbeat
    assert list(read_heartbeats(str(cdir))) == [0]


# -- RecoveryLedger --------------------------------------------------------

def test_ledger_leader_decides_followers_read(tmp_path):
    cdir = str(tmp_path / 'control')
    os.makedirs(cdir)
    leader = RecoveryLedger(cdir, host_index=0)
    follower = RecoveryLedger(cdir, host_index=1)
    assert leader.is_leader and not follower.is_leader
    assert follower.read()['attempt'] is None

    leader.decide(1, 'peer-death:host_1', mesh={'shards': 4},
                  dead_hosts=[1], detail='--model_shards 8 -> 4')
    got = follower.read()
    assert got['attempt'] == 1
    assert got['mesh'] == {'shards': 4}
    assert got['decisions'][0]['dead_hosts'] == [1]

    with pytest.raises(LedgerError):
        follower.decide(2, 'nope')


def test_ledger_follower_wait_for_attempt(tmp_path):
    cdir = str(tmp_path / 'control')
    os.makedirs(cdir)
    leader = RecoveryLedger(cdir, host_index=0)
    follower = RecoveryLedger(cdir, host_index=1)
    assert follower.wait_for_attempt(1, timeout_s=0.2, poll_s=0.05) \
        is None
    t = threading.Timer(0.15, lambda: leader.decide(1, 'hang-report',
                                                    mesh={'shards': 2}))
    t.start()
    try:
        got = follower.wait_for_attempt(1, timeout_s=5.0, poll_s=0.02)
    finally:
        t.join()
    assert got is not None and got['mesh'] == {'shards': 2}


def test_ledger_decisions_accumulate(tmp_path):
    cdir = str(tmp_path / 'control')
    os.makedirs(cdir)
    led = RecoveryLedger(cdir, host_index=0)
    led.decide(1, 'exit:3')
    led.decide(2, 'peer-death:host_2', mesh={'shards': 2})
    got = led.read()
    assert got['attempt'] == 2
    assert [d['reason'] for d in got['decisions']] == \
        ['exit:3', 'peer-death:host_2']


# -- FenceGuard ------------------------------------------------------------

def test_fence_guard_clean_exit_writes_nothing(tmp_path):
    report = str(tmp_path / 'hang_report.json')
    with FenceGuard(report, deadline_s=5.0, phase='epoch-fence',
                    step=1, on_timeout='report') as g:
        pass
    time.sleep(0.1)
    assert not g.fired and not os.path.exists(report)


def test_fence_guard_deadline_names_missing_hosts(tmp_path):
    obs = str(tmp_path / 'obs')
    report = str(tmp_path / 'hang_report.json')
    me = HostChannel(obs, host_index=0, num_hosts=3)
    peer = HostChannel(obs, host_index=1, num_hosts=3)
    me.record_fence('epoch-fence', 4)
    peer.record_fence('epoch-fence', 3)   # one fence behind
    write_tombstone(me.dir, 2, step=2)    # and one dead outright
    with FenceGuard(report, deadline_s=0.1, phase='epoch-fence', step=4,
                    channel=me, on_timeout='report') as g:
        time.sleep(0.5)                   # the "wedged collective"
    assert g.fired
    rep = json.load(open(report))
    assert rep['reason'].startswith('fence-deadline')
    assert rep['fence'] == {'phase': 'epoch-fence', 'step': 4}
    missing = {m['host']: m for m in rep['missing_hosts']}
    assert 1 in missing                    # behind this fence
    assert missing[1]['last_fence']['step'] == 3
    assert missing[2].get('dead') is True  # tombstoned
    assert rep['threads']                  # stacks for the post-mortem


def test_fence_guard_peer_that_reached_fence_is_not_missing(tmp_path):
    obs = str(tmp_path / 'obs')
    report = str(tmp_path / 'hang_report.json')
    me = HostChannel(obs, host_index=0, num_hosts=2)
    peer = HostChannel(obs, host_index=1, num_hosts=2)
    peer.record_fence('epoch-fence', 4)   # arrived (same fence)
    with FenceGuard(report, deadline_s=0.1, phase='epoch-fence', step=4,
                    channel=me, on_timeout='report') as g:
        time.sleep(0.4)
    assert g.fired
    rep = json.load(open(report))
    assert rep['missing_hosts'] == []


def test_fence_guard_completed_flag_beats_late_timer(tmp_path):
    """Timer.cancel() is a no-op once the callback has started: a fence
    completing right at the deadline must not be reported dead (and
    must not os._exit a healthy run). The completed flag set by
    __exit__ wins the race."""
    report = str(tmp_path / 'hang_report.json')
    g = FenceGuard(report, deadline_s=60.0, phase='epoch-fence', step=1,
                   on_timeout='exit')   # exit mode: a bug here would
    with g:                             # kill pytest, loudly
        pass
    g._fire()                           # the "timer fired anyway" race
    assert not g.fired
    assert not os.path.exists(report)


def test_fence_guard_rejects_unknown_on_timeout(tmp_path):
    with pytest.raises(ValueError):
        FenceGuard('r.json', 1.0, phase='x', on_timeout='explode')


def test_fence_guard_exit_path_rc(tmp_path):
    """The production mode: a missed fence deadline EXITS with the
    documented rc (attributable death, not an rc:124 hang). Needs a
    subprocess — os._exit would take pytest down with it."""
    report = str(tmp_path / 'hang_report.json')
    code = f'''
import time
from dgmc_tpu.resilience.distributed_guard import FenceGuard
with FenceGuard({report!r}, deadline_s=0.1, phase='epoch-fence',
                step=7):
    time.sleep(30)
'''
    proc = subprocess.run([sys.executable, '-c', code], cwd=REPO,
                          timeout=120, capture_output=True)
    assert proc.returncode == FENCE_TIMEOUT_RC, proc.stderr[-2000:]
    rep = json.load(open(report))
    assert rep['fence']['step'] == 7
