"""Checkpoint hardening: checksummed manifests, corrupt-latest fallback,
and the actionable-error contract for every restore edge case the issue
names (empty dir, torn latest, explicit missing/corrupt step).
"""

import json
import os

import jax
import numpy as np
import pytest

from dgmc_tpu.resilience import corrupt_checkpoint
from dgmc_tpu.train import (Checkpointer, CheckpointCorruptError,
                            create_train_state, make_train_step,
                            resume_or_init)
from dgmc_tpu.train.checkpoint import MANIFEST_DIRNAME

from tests.train.test_steps import tiny_loader, tiny_model


def _tree_equal(a, b):
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


@pytest.fixture(scope='module')
def trained():
    """Three distinguishable states from three real train steps. The
    jitted step DONATES its input state, so each kept state is a deep
    copy the next step cannot invalidate."""
    import jax.numpy as jnp
    model = tiny_model()
    batch = next(iter(tiny_loader()))
    state = create_train_state(model, jax.random.key(0), batch)
    step = make_train_step(model)
    states = []
    key = jax.random.key(1)
    for _ in range(3):
        key, sub = jax.random.split(key)
        state, _ = step(state, batch, sub)
        states.append(jax.tree.map(jnp.copy, state))
    return model, batch, states


def _save_all(tmp_path, states, **kw):
    ckpt = Checkpointer(tmp_path / 'ckpt', **kw)
    for i, s in enumerate(states, start=1):
        ckpt.save(i, s, wait=True)
    return ckpt


def _fresh(trained):
    model, batch, _states = trained
    return create_train_state(model, jax.random.key(9), batch)


def test_manifest_written_and_verifies(tmp_path, trained):
    _model, _batch, states = trained
    ckpt = _save_all(tmp_path, states)
    for step in (1, 2, 3):
        mpath = os.path.join(ckpt.directory, MANIFEST_DIRNAME,
                             f'{step}.json')
        assert os.path.exists(mpath), mpath
        assert ckpt.verify(step) == []
    ckpt.close()


def test_restore_clean_latest(tmp_path, trained):
    _model, _batch, states = trained
    ckpt = _save_all(tmp_path, states)
    restored = ckpt.restore(_fresh(trained))
    assert ckpt.restored_step == 3
    assert _tree_equal(restored.params, states[-1].params)
    ckpt.close()


@pytest.mark.parametrize('mode', ['corrupt', 'truncate'])
def test_corrupt_latest_falls_back_to_previous(tmp_path, trained, mode,
                                               capsys):
    _model, _batch, states = trained
    ckpt = _save_all(tmp_path, states)
    corrupt_checkpoint(ckpt.directory, 3, mode=mode)
    assert ckpt.verify(3), 'damage must be detectable'
    restored = ckpt.restore(_fresh(trained))
    assert ckpt.restored_step == 2
    assert _tree_equal(restored.params, states[1].params)
    assert 'falling back' in capsys.readouterr().err
    ckpt.close()


def test_every_checkpoint_corrupt_raises_actionable(tmp_path, trained):
    _model, _batch, states = trained
    ckpt = _save_all(tmp_path, states)
    for step in (1, 2, 3):
        corrupt_checkpoint(ckpt.directory, step)
    with pytest.raises(CheckpointCorruptError) as e:
        ckpt.restore(_fresh(trained))
    # The error carries per-step evidence and a next action.
    for step in (1, 2, 3):
        assert f'step {step}' in str(e.value)
    assert 'Delete' in str(e.value)
    ckpt.close()


def test_explicit_missing_step_names_available(tmp_path, trained):
    _model, _batch, states = trained
    ckpt = _save_all(tmp_path, states)
    with pytest.raises(FileNotFoundError) as e:
        ckpt.restore(_fresh(trained), step=7)
    assert '[1, 2, 3]' in str(e.value)
    ckpt.close()


def test_explicit_corrupt_step_raises_not_falls_back(tmp_path, trained):
    """A caller who PINNED a step asked for that step: silently handing
    back a different one would be worse than failing."""
    _model, _batch, states = trained
    ckpt = _save_all(tmp_path, states)
    corrupt_checkpoint(ckpt.directory, 2)
    with pytest.raises(CheckpointCorruptError):
        ckpt.restore(_fresh(trained), step=2)
    # The other steps are untouched by the pinned-step failure.
    restored = ckpt.restore(_fresh(trained), step=1)
    assert _tree_equal(restored.params, states[0].params)
    ckpt.close()


def test_explicit_step_with_fallback_walks_back(tmp_path, trained):
    """restore(step=N, fallback=True): a corrupt pinned step with the
    caller's explicit blessing falls back through OLDER steps instead of
    raising an 'every checkpoint failed' error that only tried one."""
    _model, _batch, states = trained
    ckpt = _save_all(tmp_path, states)
    corrupt_checkpoint(ckpt.directory, 3)
    restored = ckpt.restore(_fresh(trained), step=3, fallback=True)
    assert ckpt.restored_step == 2
    assert _tree_equal(restored.params, states[1].params)
    ckpt.close()


def test_resave_over_existing_step_overwrites(tmp_path, trained):
    """orbax silently no-ops save(step <= latest_step): after a corrupt-
    latest fallback the resumed run re-runs the epoch and saves the SAME
    step — that save must replace the torn step, not vanish and leave
    the corrupt bytes as the latest checkpoint forever."""
    _model, _batch, states = trained
    ckpt = _save_all(tmp_path, states)
    corrupt_checkpoint(ckpt.directory, 3)
    restored = ckpt.restore(_fresh(trained))
    assert ckpt.restored_step == 2
    ckpt.save(3, states[2], wait=True)  # the re-run epoch's save
    assert ckpt.verify(3) == [], 'manifest must match the NEW step 3'
    out = ckpt.restore(_fresh(trained))
    assert ckpt.restored_step == 3
    assert _tree_equal(out.params, states[2].params)
    ckpt.close()


def test_verify_disabled_skips_manifests(tmp_path, trained):
    _model, _batch, states = trained
    ckpt = _save_all(tmp_path, states, verify=False)
    assert not os.path.isdir(os.path.join(ckpt.directory,
                                          MANIFEST_DIRNAME))
    restored = ckpt.restore(_fresh(trained))
    assert ckpt.restored_step == 3
    assert restored is not None
    ckpt.close()


def test_retention_drops_retired_manifests(tmp_path, trained):
    _model, _batch, states = trained
    ckpt = Checkpointer(tmp_path / 'ckpt', max_to_keep=2)
    for i, s in enumerate(states, start=1):
        ckpt.save(i, s, wait=True)
    ckpt.close()
    mdir = os.path.join(ckpt.directory, MANIFEST_DIRNAME)
    kept = sorted(os.listdir(mdir))
    assert kept == ['2.json', '3.json'], kept


def test_async_save_manifests_are_complete_after_close(tmp_path, trained):
    """The CLIs save WITHOUT wait: orbax records the step in all_steps()
    before its async tmp->rename commits the step dir, so a manifest
    hashed at save() time pins an empty file table that verifies
    vacuously forever. The manifest must instead land at a later
    finalize (next save / close), with the real file contents."""
    _model, _batch, states = trained
    ckpt = Checkpointer(tmp_path / 'ckpt')
    for i, s in enumerate(states, start=1):
        ckpt.save(i, s)  # async — no wait
    ckpt.close()
    for step in (1, 2, 3):
        mpath = os.path.join(ckpt.directory, MANIFEST_DIRNAME,
                             f'{step}.json')
        with open(mpath) as f:
            assert json.load(f)['files'], f'empty manifest for step {step}'
        assert ckpt.verify(step) == []


def test_finalize_skips_uncommitted_step_and_heals_empty_manifest(
        tmp_path, trained, monkeypatch):
    _model, _batch, states = trained
    ckpt = _save_all(tmp_path, states)
    # An in-flight async step: listed by all_steps(), dir not yet
    # renamed into place — no manifest may be written for it.
    monkeypatch.setattr(ckpt, 'all_steps', lambda: [1, 2, 3, 4])
    ckpt.finalize_manifests()
    assert not os.path.exists(os.path.join(
        ckpt.directory, MANIFEST_DIRNAME, '4.json'))
    # An empty manifest left behind by the pre-fix race is healed on the
    # next finalize pass instead of disabling verification for the step.
    mpath = os.path.join(ckpt.directory, MANIFEST_DIRNAME, '2.json')
    with open(mpath, 'w') as f:
        json.dump({'step': 2, 'files': {}}, f)
    ckpt.finalize_manifests()
    with open(mpath) as f:
        assert json.load(f)['files']
    assert ckpt.verify(2) == []
    ckpt.close()


# -- resume_or_init edge cases ---------------------------------------------

def test_resume_empty_dir_is_fresh_start(tmp_path, trained):
    state = _fresh(trained)
    ckpt, out_state, start = resume_or_init(str(tmp_path / 'ck'), state)
    assert start == 1
    assert out_state is state
    ckpt.close()


def test_resume_none_dir_disables_checkpointing(trained):
    state = _fresh(trained)
    ckpt, out_state, start = resume_or_init(None, state)
    assert ckpt is None and out_state is state and start == 1


def test_resume_torn_latest_falls_back(tmp_path, trained, capsys):
    """A step directory orbax committed but whose payload was damaged
    after the fact (the ckpt-corrupt fault; also what a torn write looks
    like once the commit marker survived): resume must land on the
    previous good step, not crash, not restart from scratch."""
    _model, _batch, states = trained
    ckpt = _save_all(tmp_path, states)
    ckpt.close()
    corrupt_checkpoint(str(tmp_path / 'ckpt'), 3, mode='truncate')
    ckpt2, out_state, start = resume_or_init(str(tmp_path / 'ckpt'),
                                             _fresh(trained))
    assert start == 3  # resumed AT step 2 -> next epoch is 3
    assert _tree_equal(out_state.params, states[1].params)
    assert 'unrestorable' in capsys.readouterr().out
    ckpt2.close()


def test_resume_all_corrupt_raises_with_instructions(tmp_path, trained):
    _model, _batch, states = trained
    ckpt = _save_all(tmp_path, states)
    ckpt.close()
    for step in (1, 2, 3):
        corrupt_checkpoint(str(tmp_path / 'ckpt'), step)
    with pytest.raises(CheckpointCorruptError):
        resume_or_init(str(tmp_path / 'ckpt'), _fresh(trained))


def test_resume_guard_turned_on_adopts_plain_checkpoints(tmp_path, trained,
                                                         capsys):
    """Plain checkpoints + a guarded resume state (--guard-bad-steps added
    between runs): the structure mismatch must read as a toggle, not as
    every-checkpoint-corrupt; counters start fresh."""
    from dgmc_tpu.train import GuardedTrainState, with_guard_counters
    _model, _batch, states = trained
    _save_all(tmp_path, states).close()
    guarded = with_guard_counters(_fresh(trained))
    ckpt, out_state, start = resume_or_init(str(tmp_path / 'ckpt'), guarded)
    assert start == 4
    assert isinstance(out_state, GuardedTrainState)
    assert _tree_equal(out_state.params, states[-1].params)
    assert int(out_state.skip_count) == 0
    assert int(out_state.consec_bad) == 0
    assert '--guard-bad-steps toggled' in capsys.readouterr().err
    ckpt.close()


def test_resume_guard_turned_off_drops_the_ledger(tmp_path, trained,
                                                  capsys):
    """Guarded checkpoints + a plain resume state: adopt the weights,
    drop the counters, say so."""
    from dgmc_tpu.train import GuardedTrainState, with_guard_counters
    _model, _batch, states = trained
    _save_all(tmp_path, [with_guard_counters(s) for s in states]).close()
    ckpt, out_state, start = resume_or_init(str(tmp_path / 'ckpt'),
                                            _fresh(trained))
    assert start == 4
    assert not isinstance(out_state, GuardedTrainState)
    assert _tree_equal(out_state.params, states[-1].params)
    assert '--guard-bad-steps toggled' in capsys.readouterr().err
    ckpt.close()


def test_resume_mixed_structure_retention_keeps_newest(tmp_path, trained,
                                                       capsys):
    """Retention holding BOTH structures (the guard was toggled mid-
    history): resume must land on the NEWEST restorable step with the
    structure converted — not silently slide back to an older step that
    happens to match the requested structure."""
    from dgmc_tpu.train import GuardedTrainState, with_guard_counters
    _model, _batch, states = trained
    ckpt = Checkpointer(tmp_path / 'ckpt')
    ckpt.save(1, states[0], wait=True)               # plain
    ckpt.save(2, with_guard_counters(states[1]), wait=True)  # guarded
    ckpt.close()
    # Guard off again: newest (guarded) step must win, converted.
    ckpt2, out_state, start = resume_or_init(str(tmp_path / 'ckpt'),
                                             _fresh(trained))
    assert start == 3
    assert not isinstance(out_state, GuardedTrainState)
    assert _tree_equal(out_state.params, states[1].params)
    assert '--guard-bad-steps toggled' in capsys.readouterr().err
    ckpt2.close()


def test_resume_real_corruption_still_raises_despite_toggle_retry(
        tmp_path, trained):
    """The toggle retry must not mask genuine corruption: when every
    checkpoint is damaged, BOTH structures fail and the original
    actionable error surfaces."""
    from dgmc_tpu.train import with_guard_counters
    _model, _batch, states = trained
    _save_all(tmp_path, states).close()
    for step in (1, 2, 3):
        corrupt_checkpoint(str(tmp_path / 'ckpt'), step)
    with pytest.raises(CheckpointCorruptError):
        resume_or_init(str(tmp_path / 'ckpt'),
                       with_guard_counters(_fresh(trained)))
