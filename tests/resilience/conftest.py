"""De-flake fixture for the jax-using resilience tests: never read the
persistent XLA compilation cache (same jax-0.4.37 donation+cache bug
family as tests/parallel/conftest.py and tests/examples/conftest.py —
the checkpoint-hardening and guard tests compile donating train steps).
The fault/supervisor tests are jax-free and unaffected.
"""

import jax
import pytest


@pytest.fixture(autouse=True)
def _no_persistent_compile_cache():
    from jax._src import compilation_cache

    prev = jax.config.jax_enable_compilation_cache
    jax.config.update('jax_enable_compilation_cache', False)
    compilation_cache.reset_cache()  # un-latch is_cache_used
    try:
        yield
    finally:
        jax.config.update('jax_enable_compilation_cache', prev)
        compilation_cache.reset_cache()
