"""The fault-injection API itself: spec grammar, fire-once ledger,
checkpoint damage, transient-download arming. Every recovery path the
rest of this package tests is driven through these hooks, so their
semantics (exact step, fire once across restarts, deterministic replay
for nan-grads) are pinned here first.
"""

import json
import os

import pytest

from dgmc_tpu.resilience import faults
from dgmc_tpu.resilience.faults import (FaultInjected, FaultPlan,
                                        corrupt_checkpoint, ledger_dir,
                                        parse_spec)


# -- spec grammar ----------------------------------------------------------

@pytest.mark.parametrize('text,kind,step,arg', [
    ('raise@3', 'raise', 3, None),
    ('sigterm@1', 'sigterm', 1, None),
    ('sigkill@12', 'sigkill', 12, None),
    ('stall@4', 'stall', 4, 3600.0),
    ('stall@4:2.5', 'stall', 4, 2.5),
    ('nan-grads@7', 'nan-grads', 7, None),
    ('ckpt-truncate@2', 'ckpt-truncate', 2, None),
    ('ckpt-corrupt@2', 'ckpt-corrupt', 2, None),
    ('download-fail', 'download-fail', None, 1),
    ('download-fail:3', 'download-fail', None, 3),
    ('peer-death@4', 'peer-death', 4, None),
    ('peer-death@4:1', 'peer-death', 4, 1),
    ('straggler@2:250', 'straggler', 2, 250.0),
    ('straggler@2', 'straggler', 2, 1000.0),
    ('coord-partition@5', 'coord-partition', 5, None),
    ('collective-stall@3', 'collective-stall', 3, 3600.0),
    ('collective-stall@3:7.5', 'collective-stall', 3, 7.5),
])
def test_parse_spec(text, kind, step, arg):
    spec = parse_spec(text)
    assert (spec.kind, spec.step, spec.arg) == (kind, step, arg)


@pytest.mark.parametrize('bad', [
    'explode@3',          # unknown kind
    'raise',              # step required
    'sigkill',            # step required
    'download-fail@3',    # takes a count, not a step
    'raise@x',            # non-integer step
    'peer-death',         # step required
    'collective-stall',   # step required
])
def test_parse_spec_rejects(bad):
    with pytest.raises(ValueError):
        parse_spec(bad)


def test_spec_key_roundtrip():
    assert parse_spec('sigkill@5').key == 'sigkill@5'
    assert parse_spec('download-fail:2').key == 'download-fail'


# -- fire-once ledger ------------------------------------------------------

def test_raise_fires_at_exact_step(tmp_path):
    plan = FaultPlan(['raise@3'], state_dir=str(tmp_path))
    plan.before_step(1)
    plan.before_step(2)
    with pytest.raises(FaultInjected):
        plan.before_step(3)


def test_ledger_prevents_refire_across_restarts(tmp_path):
    """A supervised restart replays the schedule from the checkpoint; the
    ledger (written BEFORE the fault delivers) must stop the replayed
    step from re-firing — otherwise sigkill@N crash-loops forever."""
    plan = FaultPlan(['raise@3'], state_dir=str(tmp_path))
    with pytest.raises(FaultInjected):
        plan.before_step(3)
    ledger = json.load(open(tmp_path / faults.FIRED_LEDGER))
    assert ledger['fired'] == ['raise@3']
    # "Restarted process": a fresh plan over the same state_dir.
    replay = FaultPlan(['raise@3'], state_dir=str(tmp_path))
    replay.before_step(3)  # must not raise


def test_no_state_dir_refires_in_fresh_plan():
    """Without a ledger dir the fire-once record is in-memory only: the
    same plan never re-fires (monotonic steps), but a fresh plan — a new
    process without persisted state — fires again."""
    plan = FaultPlan(['raise@2'], state_dir=None)
    with pytest.raises(FaultInjected):
        plan.before_step(2)
    plan.before_step(2)  # same plan: already fired
    with pytest.raises(FaultInjected):
        FaultPlan(['raise@2'], state_dir=None).before_step(2)


def test_ledger_dir_resolution(tmp_path):
    """The ledger must survive the supervisor's per-attempt --obs-dir
    rewrite: inside attempt_<k> it climbs to the obs root."""
    assert ledger_dir('/ck', '/obs') == '/ck'
    assert ledger_dir(None, '/obs/root') == '/obs/root'
    assert ledger_dir(None, '/obs/root/attempt_3') == '/obs/root'
    assert ledger_dir(None, '/obs/attempt_x') == '/obs/attempt_x'
    assert ledger_dir(None, None) is None


def test_nan_grads_not_ledgered(tmp_path):
    """nan-grads is part of the deterministic step stream: a resumed run
    must REPLAY it to reproduce the uninterrupted trajectory, so it never
    enters the fired ledger (it is compiled into the step, not fired by
    before_step)."""
    plan = FaultPlan(['nan-grads@4'], state_dir=str(tmp_path))
    assert plan.nan_grads_step == 4
    for step in range(1, 10):
        plan.before_step(step)  # never raises, never writes the ledger
    assert not os.path.exists(tmp_path / faults.FIRED_LEDGER)


# -- distributed kinds -----------------------------------------------------

def test_straggler_sleeps_every_step_from_n(monkeypatch):
    """straggler is a CONDITION: it re-fires on every step >= N
    (including supervised replays) and never enters the ledger."""
    naps = []
    monkeypatch.setattr(faults.time, 'sleep', naps.append)
    plan = FaultPlan(['straggler@3:250'], state_dir=None)
    for step in range(1, 6):
        plan.before_step(step)
    assert naps == [0.25, 0.25, 0.25]   # steps 3, 4, 5


def test_peer_death_writes_tombstone_then_kills(tmp_path, monkeypatch):
    kills = []
    monkeypatch.setattr(faults.os, 'kill',
                        lambda pid, sig: kills.append((pid, sig)))
    monkeypatch.setattr(faults.time, 'sleep', lambda s: None)
    cdir = str(tmp_path / 'control')
    plan = FaultPlan(['peer-death@2:1'], state_dir=str(tmp_path),
                     control_dir=cdir)
    with pytest.raises(FaultInjected):   # the swallowed-kill backstop
        plan.before_step(2)
    import signal
    assert kills == [(os.getpid(), signal.SIGKILL)]
    tomb = json.load(open(os.path.join(cdir, 'host_1.tombstone.json')))
    assert tomb['host'] == 1 and tomb['step'] == 2
    # The tombstone was written (and the ledger marked) BEFORE the kill.
    assert 'peer-death@2' in json.load(
        open(tmp_path / faults.FIRED_LEDGER))['fired']


def test_peer_death_defaults_to_own_host_index(tmp_path, monkeypatch):
    monkeypatch.setattr(faults.os, 'kill', lambda pid, sig: None)
    monkeypatch.setattr(faults.time, 'sleep', lambda s: None)
    cdir = str(tmp_path / 'control')
    plan = FaultPlan(['peer-death@1'], control_dir=cdir, host_index=3)
    with pytest.raises(FaultInjected):
        plan.before_step(1)
    assert os.path.exists(os.path.join(cdir, 'host_3.tombstone.json'))


def test_coord_partition_sets_flag_once(tmp_path):
    plan = FaultPlan(['coord-partition@2'], state_dir=str(tmp_path))
    plan.before_step(1)
    assert not plan.coord_partitioned
    plan.before_step(2)
    assert plan.coord_partitioned
    # A restarted process (fresh plan, same ledger) stays healed.
    replay = FaultPlan(['coord-partition@2'], state_dir=str(tmp_path))
    replay.before_step(2)
    assert not replay.coord_partitioned


def test_collective_stall_fires_in_fence_once(tmp_path, monkeypatch):
    naps = []
    monkeypatch.setattr(faults.time, 'sleep', naps.append)
    plan = FaultPlan(['collective-stall@3:12'], state_dir=str(tmp_path))
    plan.before_step(3)      # a step is NOT a fence
    plan.before_fence(2)     # wrong step
    assert naps == []
    plan.before_fence(3)
    assert naps == [12.0]
    plan.before_fence(3)     # fire-once
    assert naps == [12.0]
    replay = FaultPlan(['collective-stall@3:12'],
                       state_dir=str(tmp_path))
    replay.before_fence(3)   # ledgered across restarts
    assert naps == [12.0]


# -- checkpoint damage -----------------------------------------------------

def _fake_step_dir(tmp_path, step=3):
    d = tmp_path / str(step) / 'default'
    d.mkdir(parents=True)
    (d / 'small.bin').write_bytes(b'x' * 64)
    (d / 'big.bin').write_bytes(bytes(range(256)) * 64)
    return d / 'big.bin'


def test_corrupt_checkpoint_truncates_largest(tmp_path):
    big = _fake_step_dir(tmp_path)
    orig = big.stat().st_size
    hit = corrupt_checkpoint(str(tmp_path), 3, mode='truncate')
    assert hit == str(big)
    assert big.stat().st_size == orig // 2


def test_corrupt_checkpoint_flips_bytes(tmp_path):
    big = _fake_step_dir(tmp_path)
    orig = big.read_bytes()
    hit = corrupt_checkpoint(str(tmp_path), 3, mode='corrupt')
    assert hit == str(big)
    damaged = big.read_bytes()
    assert len(damaged) == len(orig) and damaged != orig


def test_corrupt_checkpoint_missing_step(tmp_path):
    with pytest.raises(FileNotFoundError):
        corrupt_checkpoint(str(tmp_path), 9)


# -- transient-download arming ---------------------------------------------

def test_download_fault_budget():
    faults.arm_download_faults(2)
    try:
        assert faults.consume_download_fault()
        assert faults.consume_download_fault()
        assert not faults.consume_download_fault()
    finally:
        faults.arm_download_faults(0)


def test_download_fault_armed_by_plan():
    FaultPlan(['download-fail:3'])
    try:
        assert faults.download_faults_remaining() == 3
    finally:
        faults.arm_download_faults(0)


def test_transient_jitter_stretches_never_shrinks():
    for _ in range(50):
        d = faults.transient_jitter(2.0)
        assert 2.0 <= d <= 2.5
