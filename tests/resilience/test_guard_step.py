"""The in-graph non-finite guardrail + host-side rollback policy: a bad
step must freeze the WHOLE update (params, optimizer, batch stats) while
the step counter and skip ledger advance, and M consecutive bad steps
must roll back to the last good snapshot.

Driven by the deterministic ``nan-grads@N`` fault — the injection is
compiled into the step, so the bad step lands at exactly N.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dgmc_tpu.resilience import RollbackGuard
from dgmc_tpu.train import (create_train_state, make_train_step,
                            with_guard_counters)
from dgmc_tpu.train.state import GuardedTrainState

from tests.train.test_steps import tiny_loader, tiny_model


def _tree_equal(a, b):
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


@pytest.fixture(scope='module')
def _model_batch():
    model = tiny_model()
    batch = next(iter(tiny_loader()))
    return model, batch


@pytest.fixture
def setup(_model_batch):
    """Fresh state per test: the jitted steps donate their input state,
    so a shared state would be invalidated by the first test that runs."""
    model, batch = _model_batch
    state = with_guard_counters(
        create_train_state(model, jax.random.key(0), batch))
    return model, batch, state


def test_with_guard_counters_structure(setup):
    _model, _batch, state = setup
    assert isinstance(state, GuardedTrainState)
    assert state.skip_count.dtype == jnp.int32
    assert int(state.skip_count) == 0 and int(state.consec_bad) == 0


def test_bad_step_freezes_update_and_counts(setup):
    model, batch, state = setup
    step = make_train_step(model, guard=True, fault_nan_step=2)
    key = jax.random.key(1)

    key, sub = jax.random.split(key)
    state, out = step(state, batch, sub)
    assert not bool(out['bad_step'])

    before = jax.tree.map(jnp.copy, {'params': state.params,
                                     'opt': state.opt_state,
                                     'bs': state.batch_stats})
    step_before = int(state.step)
    key, sub = jax.random.split(key)
    state, out = step(state, batch, sub)  # nan-grads fires here
    assert bool(out['bad_step'])
    assert _tree_equal(state.params, before['params'])
    assert _tree_equal(state.opt_state, before['opt'])
    assert _tree_equal(state.batch_stats, before['bs'])
    # The step counter still advances: deterministic streams (and the
    # nan-grads indexing itself) stay aligned across skips.
    assert int(state.step) == step_before + 1
    assert int(state.skip_count) == 1
    assert int(state.consec_bad) == 1

    # A good step trains again and resets the consecutive counter (the
    # cumulative skip ledger survives).
    key, sub = jax.random.split(key)
    state, out = step(state, batch, sub)
    assert not bool(out['bad_step'])
    assert not _tree_equal(state.params, before['params'])
    assert int(state.skip_count) == 1
    assert int(state.consec_bad) == 0


def test_unguarded_step_unchanged(setup):
    """guard=False still returns a plain update with no ledger keys."""
    model, batch, _state = setup
    state = create_train_state(model, jax.random.key(0), batch)
    step = make_train_step(model)
    state, out = step(state, batch, jax.random.key(1))
    assert 'bad_step' not in out and 'skip_count' not in out


def test_rollback_after_m_consecutive(setup):
    model, batch, state = setup
    # NaN every step from 1 on: consec_bad ratchets with no good step.
    step = make_train_step(model, guard=True, fault_nan_step=1)
    # (fault_nan_step fires when state.step == 0 only; emulate permanent
    # badness by re-zeroing the step counter each iteration.)
    guard = RollbackGuard(max_consecutive=3)
    guard.note_good(state, step=0)
    good_params = jax.tree.map(jnp.copy, state.params)

    key = jax.random.key(1)
    rolled_at = None
    for i in range(1, 5):
        key, sub = jax.random.split(key)
        state, out = step(state.replace(step=jnp.zeros((), jnp.int32)),
                          batch, sub)
        assert bool(out['bad_step'])
        state, rolled = guard.maybe_rollback(state, int(state.consec_bad),
                                             step=i)
        if rolled:
            rolled_at = i
            break
    assert rolled_at == 3
    assert guard.rollbacks == 1
    assert _tree_equal(state.params, good_params)
    # The ledger survives the rollback; the consecutive counter resets.
    assert int(state.skip_count) == 3
    assert int(state.consec_bad) == 0


def test_rollback_without_snapshot_reports_and_holds(setup, capsys):
    _model, _batch, state = setup
    guard = RollbackGuard(max_consecutive=2)
    out_state, rolled = guard.maybe_rollback(state, 5, step=1)
    assert not rolled and out_state is state
    assert 'no good snapshot' in capsys.readouterr().err


def test_rollback_disabled_with_zero(setup):
    _model, _batch, state = setup
    guard = RollbackGuard(max_consecutive=0)
    guard.note_good(state, step=0)
    _out, rolled = guard.maybe_rollback(state, 100, step=1)
    assert not rolled
