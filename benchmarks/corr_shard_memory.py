"""Does correspondence sharding actually cut per-device memory?

VERDICT round-2 item 6: the corr-sharded (model-parallel) path had
correctness coverage but no evidence that sharding ``S_hat``/``S_idx``
rows reduces the per-device activation footprint. This compiles the
DBP15K-shape sparse training step on a virtual 8-device CPU mesh with
and without ``corr_sharding`` and records each executable's
``memory_analysis()`` (argument / output / temp bytes — temp is where
activations live). Writes ``benchmarks/corr_shard_memory.json``.

Run:  python benchmarks/corr_shard_memory.py
"""

import json
import os
import sys

os.environ['JAX_PLATFORMS'] = 'cpu'
os.environ['XLA_FLAGS'] = (os.environ.get('XLA_FLAGS', '') +
                           ' --xla_force_host_platform_device_count=8')

import jax  # noqa: E402

jax.config.update('jax_platforms', 'cpu')

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))


def analyze(model_shards):
    import bench
    from dgmc_tpu.models import DGMC, RelCNN
    from dgmc_tpu.train import create_train_state, make_train_step
    from dgmc_tpu.utils.data import PairBatch

    rng = np.random.RandomState(0)
    s = bench._kg_side(bench.SP_N_S, bench.SP_E_S, bench.SP_DIM, rng)
    t = bench._kg_side(bench.SP_N_T, bench.SP_E_T, bench.SP_DIM, rng)
    y = np.full((1, bench.SP_N_S), -1, np.int32)
    y[0, :4500] = rng.permutation(bench.SP_N_T)[:4500]
    batch = PairBatch(s=s, t=t, y=y, y_mask=y >= 0)

    corr = None
    if model_shards > 1:
        from dgmc_tpu.parallel import corr_sharding as mk_corr, make_mesh
        mesh = make_mesh(data=1, model=model_shards)
        corr = mk_corr(mesh)

    psi_1 = RelCNN(bench.SP_DIM, 256, num_layers=3, dropout=0.5)
    psi_2 = RelCNN(32, 32, num_layers=3)
    model = DGMC(psi_1, psi_2, num_steps=bench.NUM_STEPS, k=bench.SP_K,
                 topk_block=bench.SP_TOPK_BLOCK, corr_sharding=corr)
    tiny = PairBatch(s=bench._kg_side(32, 64, bench.SP_DIM, rng),
                     t=bench._kg_side(32, 64, bench.SP_DIM, rng),
                     y=np.zeros((1, 32), np.int32),
                     y_mask=np.ones((1, 32), bool))
    state = create_train_state(model, jax.random.key(0), tiny,
                               learning_rate=1e-3)
    step = make_train_step(model, loss_on_s0=False)
    compiled = step.lower(state, batch, jax.random.key(1)).compile()
    ma = compiled.memory_analysis()
    gib = 2.0 ** 30
    return {
        'model_shards': model_shards,
        'argument_gib': round(ma.argument_size_in_bytes / gib, 3),
        'output_gib': round(ma.output_size_in_bytes / gib, 3),
        'temp_gib': round(ma.temp_size_in_bytes / gib, 3),
    }


def main():
    results = [analyze(1), analyze(8)]
    base, sharded = results
    results_doc = {
        'shape': 'DBP15K sparse train step, 15000x20000 k=10 steps=10',
        'note': ('memory_analysis() of the SPMD-partitioned executable; '
                 'temp bytes are per-device activation/workspace'),
        'runs': results,
        'temp_reduction': round(
            base['temp_gib'] / max(sharded['temp_gib'], 1e-9), 2),
    }
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       'corr_shard_memory.json')
    with open(out, 'w') as f:
        json.dump(results_doc, f, indent=1)
    print(json.dumps(results_doc))


if __name__ == '__main__':
    main()
