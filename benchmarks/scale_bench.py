"""Streamed-S million-entity scale benchmark (ROADMAP item 4).

Drives the DBP15K CLI's partition-rule streamed layout
(``--row_shards N --stream_chunk M``, ``dgmc_tpu/parallel/rules.py``) on
a synthetic KG-alignment pair of arbitrary size
(``dgmc_tpu/data/synthetic.synthetic_kg_alignment``) — the headline
record is the 10⁶×10⁶-entity pair, whose dense correspondence matrix
(4 TB) no machine holds and whose 15k-scale sparse ancestor already
peaked at 2.3 GiB HBM on one chip.

Round 8 protocol — three legs:

1. the N-device mesh (default 8), supervised (``--supervise`` + armed
   watchdog): S row-sharded over ``data``, candidate search streamed
   per shard through the DOUBLE-BUFFERED chunk pipeline with targets
   RING-rotated over the same axis (``streamed_rules`` defaults since
   the pipelining rewrite — boundary permutes overlap the per-tile
   top-k instead of serializing it);
2. the 1-device reference: same streamed path, unsharded — the
   weak-scaling efficiency anchor;
3. the OFFLOAD leg (``--offload-corpus``, on by default): a ~10M-row
   (``--offload-rows``, default 2^23) corpus ψ₁ table resident in HOST
   RAM, shortlisted through ``python -m dgmc_tpu.ops.offload`` — the
   N-deep device prefetch ring streams chunks to every device while
   the shortlist streams back, so per-device static memory stays at
   the per-chunk executable's bound however big the corpus
   (``--prefetch-depth``; a leading prefix is verified bit-exact
   against the device-resident path).

Each run records through the standard obs stack (``RunObserver`` step
timings, ``--aot_compile`` static per-device memory bounds from
``memory_analysis``, ``obs.cost`` stage attribution) and the N-device run
is merged by ``obs.aggregate`` into the per-device skew summary. The
driver then writes one committed JSON record (``SCALE_r08.json``) with
step times, per-device memory, scaling efficiency vs 1 device, and the
offload-leg account (the ``offload`` column of ``obs.timeline``).

On this container the "devices" are XLA virtual CPU devices on one
socket (no parallel silicon), so the efficiency number records
machinery + memory behavior, not real scaling — same caveat as
``MULTICHIP_r06.json``; the real-accelerator rerun is driver-side.
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def cli_argv(args, obs_dir, row_shards, n_s=None, e_s=None):
    n_s = args.nodes if n_s is None else n_s
    e_s = args.edges if e_s is None else e_s
    argv = [
        sys.executable, '-m', 'dgmc_tpu.experiments.dbp15k',
        '--synthetic',
        '--syn_nodes_s', str(n_s), '--syn_nodes_t', str(args.nodes),
        '--syn_edges_s', str(e_s),
        '--syn_edges_t', str(int(args.edges * 1.25)),
        '--syn_dim', str(args.dim),
        '--dim', str(args.psi_dim), '--rnd_dim', str(args.rnd_dim),
        '--num_layers', '1', '--num_steps', str(args.num_steps),
        '--k', str(args.k),
        '--epochs', str(args.epochs),
        '--phase1_epochs', str(args.phase1_epochs),
        '--seed', str(args.seed),
        '--stream_chunk', str(args.chunk),
        '--topk_block', str(args.block),
        # The CLI's library default is the bf16 compute policy — a
        # TPU-measured win (DISPATCH_DEFAULTS.md). This container's CPU
        # backend EMULATES bf16 (measured >10x on a whole phase-1 step:
        # 96+ min and counting vs ~7 min f32 at 2^20), so the scale
        # record pins the f32 policy explicitly.
        '--f32',
        '--aot_compile',
        '--obs-dir', obs_dir,
        '--supervise', '--max-restarts', '2',
        '--watchdog-deadline', str(args.watchdog),
    ]
    if row_shards > 1:
        argv += ['--row_shards', str(row_shards)]
    if args.obs_port is not None:
        # Live telemetry on the CLI child: with the default 0 each leg
        # binds its own free port and advertises it in heartbeat.json,
        # so the supervisor/aggregate discover it without coordination
        # (a fixed port would collide between the 8-dev and 1-dev legs).
        argv += ['--obs-port', str(args.obs_port)]
    return argv


def anchor_cpu_share(args):
    """CPU cores the 1-device anchor leg is pinned to (``taskset``):
    its fair per-device share of the socket. The N-device leg runs N
    virtual devices on the whole socket, so each device effectively
    owns ``cores/N``; an anchor free to spread one device's work over
    every core is comparing one device against N devices' silicon, and
    the 'weak-scaling' ratio reads ~0.88 from that artifact alone
    (r07's recorded gap — measured directly: the 2^18 slice search
    takes 20.2 s on the full socket vs 23.8 s on its 3-core share,
    against 23.3 s per sharded step). Returns a core count, or 0 =
    unpinned (``--anchor-cpus 0``, the r07 protocol). Validates up
    front — ``main`` resolves this BEFORE any leg runs, so an unusable
    explicit value fails in seconds, not after the 8-device leg's wall
    clock."""
    import shutil
    if str(args.anchor_cpus).lower() in ('0', 'off', 'none'):
        return 0
    if str(args.anchor_cpus) == 'auto':
        if shutil.which('taskset') is None:
            return 0
        return max(1, (os.cpu_count() or args.devices) // args.devices)
    try:
        n = int(args.anchor_cpus)
    except ValueError:
        raise SystemExit(
            f'--anchor-cpus must be "auto", 0/off, or an integer core '
            f'count; got {args.anchor_cpus!r}')
    if n > 0 and shutil.which('taskset') is None:
        raise SystemExit(
            f'--anchor-cpus {n} requires taskset(1), which this box '
            f'does not have; pass --anchor-cpus 0 for the unpinned '
            f'(r07) protocol')
    return max(0, n)


def run_leg(args, name, row_shards, n_devices, n_s=None, e_s=None,
            pin_cpus=0):
    obs_dir = os.path.join(args.workdir, f'obs_{name}')
    env = dict(
        os.environ,
        JAX_PLATFORMS='cpu',
        XLA_FLAGS=(os.environ.get('XLA_FLAGS', '')
                   + f' --xla_force_host_platform_device_count='
                     f'{n_devices}'),
        # The jax-0.4.37 persistent-cache + donation family (PR 3): scale
        # evidence must never come from a deserialized executable.
        JAX_ENABLE_COMPILATION_CACHE='false',
    )
    log_path = os.path.join(args.workdir, f'{name}.log')
    done = os.path.join(obs_dir, 'recovery.json')
    # The pinning actually APPLIED to this leg, persisted beside its
    # telemetry: a --reuse collect-only rerun must report the pin the
    # completed leg ran under, not whatever the current invocation
    # would have used (a reused unpinned r07-era anchor documented as
    # pinned would falsify the efficiency number's provenance).
    pin_path = os.path.join(args.workdir, f'{name}.pin.json')
    if args.reuse and os.path.exists(done) and json.load(
            open(done)).get('outcome') == 'completed':
        # Collect-only rerun: the leg already completed in this workdir;
        # its wall clock comes from the supervisor's attempt ledger.
        rc = 0
        wall = sum(a.get('end_time', 0.0) - a.get('start_time', 0.0)
                   for a in json.load(open(done)).get('attempts', []))
        pin_cpus = (json.load(open(pin_path)).get('pin_cpus', 0)
                    if os.path.exists(pin_path) else 0)
        print(f'# {name}: reusing completed leg in {obs_dir} '
              f'(ran with pin_cpus={pin_cpus})', flush=True)
    else:
        t0 = time.time()
        argv = cli_argv(args, obs_dir, row_shards, n_s=n_s, e_s=e_s)
        if pin_cpus:
            argv = ['taskset', '-c', f'0-{pin_cpus - 1}'] + argv
        with open(pin_path, 'w') as f:
            json.dump({'pin_cpus': pin_cpus}, f)
        with open(log_path, 'w') as log:
            rc = subprocess.run(
                argv, cwd=REPO, env=env, stdout=log,
                stderr=subprocess.STDOUT).returncode
        wall = time.time() - t0
    print(f'# {name}: rc={rc} wall={wall:.0f}s (log: {log_path})',
          flush=True)
    # A supervised run's telemetry lands in attempt_<k>/ subdirs; the
    # run's outcome is the FINAL attempt (obs.report binds the root the
    # same way).
    final_dir = obs_dir
    attempts = sorted(
        (d for d in os.listdir(obs_dir) if d.startswith('attempt_')),
        key=lambda d: int(d.split('_')[-1])) if os.path.isdir(obs_dir) \
        else []
    if attempts:
        final_dir = os.path.join(obs_dir, attempts[-1])
    if row_shards > 1:
        subprocess.run([sys.executable, '-m', 'dgmc_tpu.obs.aggregate',
                        final_dir], cwd=REPO, env=env,
                       stdout=subprocess.DEVNULL)
    report = {}
    try:
        out = subprocess.run([sys.executable, '-m', 'dgmc_tpu.obs.report',
                              obs_dir, '--json'], cwd=REPO, env=env,
                             capture_output=True, text=True)
        report = json.loads(out.stdout)
    except Exception as e:
        report = {'error': f'{type(e).__name__}: {e}'}
    recovery = {}
    rec_path = os.path.join(obs_dir, 'recovery.json')
    if os.path.exists(rec_path):
        with open(rec_path) as f:
            recovery = json.load(f)
    aot_memory = {}
    metrics_path = os.path.join(final_dir, 'metrics.jsonl')
    if os.path.exists(metrics_path):
        with open(metrics_path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                ev = str(rec.get('event', ''))
                if ev.startswith('aot_memory_'):
                    aot_memory[ev[len('aot_memory_'):]] = {
                        k: rec[k] for k in ('argument_bytes',
                                            'output_bytes', 'temp_bytes',
                                            'total_bytes') if k in rec}
    return {'rc': rc, 'wall_s': round(wall, 1), 'obs_dir': obs_dir,
            'report': report, 'recovery': recovery,
            'aot_memory': aot_memory, 'pin_cpus': pin_cpus,
            'hang_report': os.path.exists(
                os.path.join(obs_dir, 'hang_report.json'))}


def run_offload_leg(args):
    """The host-RAM offload leg: ``python -m dgmc_tpu.ops.offload`` on
    the full virtual-device mesh, watchdog-armed through the standard
    obs stack; returns the driver's JSON record plus rc/wall. Under
    ``--reuse`` a completed record in the workdir is collected instead
    of re-running the ~50-minute sweep (the same contract as the
    supervised legs' recovery.json reuse)."""
    obs_dir = os.path.join(args.workdir, 'obs_offload')
    record_path = os.path.join(args.workdir, 'offload_record.json')
    if args.reuse and os.path.exists(record_path):
        with open(record_path) as f:
            saved = json.load(f)
        if saved.get('record', {}).get('metric') == 'offloaded_shortlist':
            print(f'# offload: reusing completed leg in {obs_dir}',
                  flush=True)
            return saved
    env = dict(
        os.environ,
        JAX_PLATFORMS='cpu',
        XLA_FLAGS=(os.environ.get('XLA_FLAGS', '')
                   + f' --xla_force_host_platform_device_count='
                     f'{args.devices}'),
        JAX_ENABLE_COMPILATION_CACHE='false',
    )
    log_path = os.path.join(args.workdir, 'offload.log')
    argv = [
        sys.executable, '-m', 'dgmc_tpu.ops.offload',
        '--rows', str(args.offload_rows),
        '--targets', str(args.offload_targets),
        '--dim', str(args.psi_dim), '--k', str(args.k),
        '--chunk', str(args.offload_chunk),
        '--block', str(args.block),
        '--prefetch-depth', str(args.prefetch_depth),
        '--seed', str(args.seed),
        '--obs-dir', obs_dir,
        '--watchdog-deadline', str(args.watchdog),
    ]
    t0 = time.time()
    with open(log_path, 'w') as log:
        proc = subprocess.run(argv, cwd=REPO, env=env,
                              stdout=subprocess.PIPE, stderr=log,
                              text=True)
    wall = time.time() - t0
    record = {}
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            record = json.loads(line)
            break
        except ValueError:
            continue
    print(f'# offload: rc={proc.returncode} wall={wall:.0f}s '
          f'(log: {log_path})', flush=True)
    leg = {'rc': proc.returncode, 'wall_s': round(wall, 1),
           'obs_dir': obs_dir, 'record': record,
           'hang_report': os.path.exists(
               os.path.join(obs_dir, 'hang_report.json'))}
    if proc.returncode == 0 and record:
        with open(record_path, 'w') as f:
            json.dump(leg, f)
    return leg


def summarize(args, leg8, leg1, offload=None):
    rep8, rep1 = leg8['report'], leg1['report']
    p50_8 = rep8.get('step_p50_s')
    p50_1 = rep1.get('step_p50_s')
    mem8 = leg8['aot_memory'].get('train_step', {})
    mem1 = leg1['aot_memory'].get('train_step', {})
    gib = 2 ** 30
    out = {
        'round': args.round,
        'metric': 'streamed_sharded_scale',
        'shape': (f'{args.nodes}x{args.nodes} k={args.k} '
                  f'chunk={args.chunk} block={args.block} '
                  f'dim={args.dim}'),
        'n_devices': args.devices,
        'mode': (f'supervised streamed-S synthetic KG alignment '
                 f'(dbp15k.py --synthetic --row_shards {args.devices} '
                 f'--stream_chunk {args.chunk} --aot_compile) under '
                 f'--supervise --watchdog-deadline {args.watchdog}; '
                 f'double-buffered chunk pipeline + ring-rotated '
                 f'target shards (streamed_rules defaults since the '
                 f'overlap rewrite)'),
        'environment': {
            'platform': ('cpu (XLA --xla_force_host_platform_device_'
                         f'count={args.devices}; virtual devices on one '
                         'socket — machinery + memory evidence, not '
                         'parallel silicon)'),
        },
        'config': {
            'nodes': args.nodes, 'edges_s': args.edges,
            'edges_t': int(args.edges * 1.25), 'dim': args.dim,
            'psi_dim': args.psi_dim, 'rnd_dim': args.rnd_dim,
            'k': args.k, 'num_steps': args.num_steps,
            'epochs': args.epochs, 'phase1_epochs': args.phase1_epochs,
            'stream_chunk': args.chunk, 'topk_block': args.block,
            'seed': args.seed,
        },
        'supervision': {
            'outcome_8dev': leg8['recovery'].get('outcome'),
            'restarts_8dev': leg8['recovery'].get('restarts'),
            'outcome_1dev': leg1['recovery'].get('outcome'),
            'restarts_1dev': leg1['recovery'].get('restarts'),
            'hang_report': leg8['hang_report'] or leg1['hang_report'],
            'watchdog_deadline_s': args.watchdog,
        },
        'anchor_mode': (
            ('weak-scaling slice: 1dev leg runs N_s/devices source rows '
             'against the full target set (equal per-device work)'
             if args.anchor == 'slice' else
             'strong: 1dev leg runs the full pair')
            # Provenance from the leg that RAN (run_leg persists the
            # applied pin beside its telemetry), never from the current
            # invocation's flags — a --reuse collect must not relabel
            # an unpinned anchor as pinned.
            + (f'; anchor pinned to its fair per-device core share '
               f'({leg1.get("pin_cpus")} of {os.cpu_count()} cores '
               f'via taskset — the N-device leg runs N virtual devices '
               f'on one socket, so an unpinned anchor would compare '
               f'one device against N devices\' silicon)'
               if leg1.get('pin_cpus') else
               '; anchor unpinned (whole socket — the r07 protocol)')),
        'timing': {
            'step_p50_ms_8dev': None if p50_8 is None
            else round(p50_8 * 1e3, 1),
            'step_p50_ms_1dev': None if p50_1 is None
            else round(p50_1 * 1e3, 1),
            'scaling_efficiency_vs_1dev': None
            if not (p50_8 and p50_1) else round(p50_1 / p50_8, 3),
            'per_device_step_skew_ratio': rep8.get(
                'skew', {}).get('step_time_ratio'),
            'devices_reporting': len(rep8.get('device_steps', {})),
            'wall_s_8dev': leg8['wall_s'], 'wall_s_1dev': leg1['wall_s'],
        },
        'memory': {
            'per_device_static_gib_8dev': None if not mem8 else round(
                mem8['total_bytes'] / gib, 3),
            'per_device_static_gib_1dev': None if not mem1 else round(
                mem1['total_bytes'] / gib, 3),
            'per_device_static_bytes_8dev': mem8 or None,
            'per_device_static_bytes_1dev': mem1 or None,
            'host_peak_rss_gib_8dev': None
            if not rep8.get('peak_memory_bytes') else round(
                rep8['peak_memory_bytes'] / gib, 3),
            'host_peak_rss_gib_1dev': None
            if not rep1.get('peak_memory_bytes') else round(
                rep1['peak_memory_bytes'] / gib, 3),
            'single_chip_flagship_peak_gib': 2.3,
        },
    }
    if offload is not None:
        rec = offload.get('record') or {}
        ost = rec.get('offload') or {}
        mem_off = rec.get('per_device_static_bytes') or {}
        out['offload'] = {
            'outcome': ('completed' if offload['rc'] == 0
                        and rec.get('metric') == 'offloaded_shortlist'
                        else f'rc:{offload["rc"]}'),
            'rows': rec.get('rows'),
            'targets': rec.get('targets'),
            'chunk': rec.get('chunk'),
            'prefetch_depth': ost.get('prefetch_depth'),
            'host_resident_bytes': ost.get('host_resident_bytes'),
            'bytes_streamed': ost.get('bytes_streamed'),
            'ring_misses': ost.get('ring_misses'),
            'wall_s': offload['wall_s'],
            'rows_per_sec': rec.get('rows_per_sec'),
            'per_device_static_gib': None if not mem_off else round(
                mem_off['total_bytes'] / gib, 3),
            'per_device_static_bytes': mem_off or None,
            'verified_rows': rec.get('verified_rows'),
            'verified_equal': rec.get('verified_equal'),
            'hang_report': offload['hang_report'],
        }
    out['analysis'] = (
        'Round 8: the chunk loop is a pipeline, and the corpus no '
        'longer has to fit on device. The 2^20 x 2^20 supervised leg '
        'runs the rewritten streamed layout - double-buffered source '
        "chunks (iteration k+1's fetch rides the scan carry, "
        "independent of iteration k's compute) and ring-rotated "
        'target shards whose boundary collective-permute is issued a '
        'rotation ahead of the per-tile top-k (per-device h_t drops '
        'to one shard; the trip-amplified schedule model pins the '
        'overlap at >= the 0.24 committed budget, 2x the pre-rewrite '
        'pin). The offload leg goes an order of magnitude up the '
        'source axis: the corpus psi_1 table lives in HOST RAM and '
        'streams through the N-deep device prefetch ring while the '
        'shortlist streams back, so per-device static memory is the '
        "per-chunk executable's bound - flat vs r07's 1.04 "
        'GiB/device however many rows the corpus holds - with a '
        'leading prefix verified bit-exact against the '
        'device-resident path. Timing on virtual CPU devices records '
        'machinery, not silicon; the f32 policy stays pinned (this '
        'CPU backend emulates bf16 >10x slower), and the '
        'real-accelerator rerun remains a config change, not new '
        'code.')
    return out


def main(argv=None):
    parser = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    # 2^20 = 1,048,576 entities per side: >10^6, and divisible by every
    # mesh/chunk/block power of two in play.
    parser.add_argument('--nodes', type=int, default=1 << 20)
    parser.add_argument('--edges', type=int, default=1 << 22)
    parser.add_argument("--dim", type=int, default=16,
                        help='entity feature width (syn_dim)')
    parser.add_argument('--psi-dim', dest='psi_dim', type=int, default=16,
                        help='psi_1 width = candidate-search C')
    parser.add_argument('--rnd_dim', type=int, default=8)
    parser.add_argument('--k', type=int, default=10)
    parser.add_argument('--num-steps', dest='num_steps', type=int,
                        default=1)
    parser.add_argument('--epochs', type=int, default=2)
    parser.add_argument('--phase1-epochs', dest='phase1_epochs', type=int,
                        default=1)
    parser.add_argument('--chunk', type=int, default=2048)
    parser.add_argument('--block', type=int, default=8192,
                        help='candidate-search tile width for the scan '
                             'paths: the CPU-measured optimum at this '
                             'scale (the 256 library default is the '
                             'TPU-sweep number; on CPU the wider tile '
                             'amortizes the per-tile top_k pass)')
    parser.add_argument('--devices', type=int, default=8)
    parser.add_argument('--seed', type=int, default=7)
    parser.add_argument('--watchdog', type=int, default=7200)
    parser.add_argument('--obs-port', '--obs_port', dest='obs_port',
                        type=int, default=None, metavar='PORT',
                        help='arm the live telemetry plane on each CLI '
                             'leg (pass 0: every leg picks a free port '
                             'and advertises it in its heartbeat.json)')
    parser.add_argument('--round', type=int, default=8)
    parser.add_argument('--offload-corpus', '--offload_corpus',
                        dest='offload_corpus', default=True,
                        action='store_true',
                        help='run the host-RAM offload leg (on by '
                             'default; --no-offload-corpus skips it)')
    parser.add_argument('--no-offload-corpus', dest='offload_corpus',
                        action='store_false')
    parser.add_argument('--offload-rows', dest='offload_rows', type=int,
                        default=1 << 23,
                        help='offload-leg corpus rows (>= 2^23 = the '
                             '~10M-row r08 target)')
    parser.add_argument('--offload-targets', dest='offload_targets',
                        type=int, default=1 << 17)
    parser.add_argument('--offload-chunk', dest='offload_chunk',
                        type=int, default=1 << 14,
                        help='offload-leg rows per device chunk: the '
                             'compiled per-chunk program holds TWO '
                             '[chunk, block] f32 score tiles, so '
                             'chunk=2^14 x block=8192 measures 1.01 '
                             'GiB static per device (SCALE_r08.json) — '
                             'only ~3%% headroom under the 1.04 '
                             'GiB/device ceiling; size up with the '
                             'measured record, not the single-tile '
                             'arithmetic')
    parser.add_argument('--prefetch-depth', '--prefetch_depth',
                        dest='prefetch_depth', type=int, default=2,
                        help='offload-leg device prefetch ring depth '
                             '(benchmarks/DISPATCH_DEFAULTS.md)')
    parser.add_argument('--anchor-cpus', dest='anchor_cpus', type=str,
                        default='auto',
                        help='pin the 1-device anchor leg to this many '
                             'CPU cores via taskset ("auto" = '
                             'cores/devices, the fair per-device share '
                             'of the socket; 0/off = unpinned, the r07 '
                             'protocol). On a virtual-device socket an '
                             'unpinned anchor measures one device '
                             'against N devices\' silicon')
    parser.add_argument('--anchor', choices=['slice', 'full'],
                        default='slice',
                        help='1-device scaling anchor: "slice" = '
                             'weak-scaling (one device\'s row share, '
                             'full targets), "full" = the whole pair '
                             'on one device (~devices x the wall '
                             'clock)')
    parser.add_argument('--reuse', action='store_true',
                        help='skip any leg whose workdir obs dir '
                             'already holds a completed recovery.json '
                             '(collect-only rerun)')
    parser.add_argument('--workdir', type=str, default='/tmp/scale_bench')
    parser.add_argument('--out', type=str,
                        default=os.path.join(REPO, 'benchmarks',
                                             'SCALE_r08.json'))
    args = parser.parse_args(argv)
    os.makedirs(args.workdir, exist_ok=True)
    # Resolve (and validate) the anchor pin BEFORE any leg burns wall
    # clock: a bad --anchor-cpus fails here, not after the 8-dev leg.
    pin = anchor_cpu_share(args)

    leg8 = run_leg(args, f'{args.devices}dev', args.devices, args.devices)
    if args.anchor == 'slice':
        # Weak-scaling anchor: the 1-device leg runs ONE device's share of
        # source rows (N_s / devices) against the FULL target set — the
        # per-device work of the sharded leg, so
        # t_1dev(slice) / t_Ndev(full) reads as weak-scaling efficiency.
        # The full 10^6-row single-device leg is ~devices x this wall
        # clock (~10 h on this container) for a number with the same
        # meaning; 'full' remains available for a real chip.
        leg1 = run_leg(args, '1dev', 0, 1,
                       n_s=args.nodes // args.devices,
                       e_s=args.edges // args.devices,
                       pin_cpus=pin)
    else:
        leg1 = run_leg(args, '1dev', 0, 1, pin_cpus=pin)
    offload = run_offload_leg(args) if args.offload_corpus else None
    out = summarize(args, leg8, leg1, offload)
    with open(args.out, 'w') as f:
        json.dump(out, f, indent=1)
        f.write('\n')
    print(json.dumps({k: out[k] for k in ('timing', 'memory',
                                          'supervision', 'offload')
                      if k in out}, indent=1))
    print(f'# wrote {args.out}')
    ok = leg8['rc'] == 0 and leg1['rc'] == 0 and (
        offload is None or offload['rc'] == 0)
    return 0 if ok else 1


if __name__ == '__main__':
    sys.exit(main())
