"""Streamed-S million-entity scale benchmark (ROADMAP item 3).

Drives the DBP15K CLI's partition-rule streamed layout
(``--row_shards N --stream_chunk M``, ``dgmc_tpu/parallel/rules.py``) on
a synthetic KG-alignment pair of arbitrary size
(``dgmc_tpu/data/synthetic.synthetic_kg_alignment``) — the headline
record is the 10⁶×10⁶-entity pair, whose dense correspondence matrix
(4 TB) no machine holds and whose 15k-scale sparse ancestor already
peaked at 2.3 GiB HBM on one chip.

Two supervised runs (``--supervise`` + armed watchdog — a hang becomes
``hang_report.json`` + retry, not rc:124-with-nothing, the r01–r05
multichip lesson):

1. the N-device mesh (default 8): S row-sharded over ``data``, candidate
   search streamed per shard;
2. the 1-device reference: same streamed path, unsharded — the
   scaling-efficiency anchor.

Each run records through the standard obs stack (``RunObserver`` step
timings, ``--aot_compile`` static per-device memory bounds from
``memory_analysis``, ``obs.cost`` stage attribution) and the N-device run
is merged by ``obs.aggregate`` into the per-device skew summary. The
driver then writes one committed JSON record (``SCALE_r07.json``) with
step times, per-device memory, and scaling efficiency vs 1 device.

On this container the "devices" are XLA virtual CPU devices on one
socket (no parallel silicon), so the efficiency number records
machinery + memory behavior, not real scaling — same caveat as
``MULTICHIP_r06.json``; the real-accelerator rerun is driver-side.
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def cli_argv(args, obs_dir, row_shards, n_s=None, e_s=None):
    n_s = args.nodes if n_s is None else n_s
    e_s = args.edges if e_s is None else e_s
    argv = [
        sys.executable, '-m', 'dgmc_tpu.experiments.dbp15k',
        '--synthetic',
        '--syn_nodes_s', str(n_s), '--syn_nodes_t', str(args.nodes),
        '--syn_edges_s', str(e_s),
        '--syn_edges_t', str(int(args.edges * 1.25)),
        '--syn_dim', str(args.dim),
        '--dim', str(args.psi_dim), '--rnd_dim', str(args.rnd_dim),
        '--num_layers', '1', '--num_steps', str(args.num_steps),
        '--k', str(args.k),
        '--epochs', str(args.epochs),
        '--phase1_epochs', str(args.phase1_epochs),
        '--seed', str(args.seed),
        '--stream_chunk', str(args.chunk),
        '--topk_block', str(args.block),
        # The CLI's library default is the bf16 compute policy — a
        # TPU-measured win (DISPATCH_DEFAULTS.md). This container's CPU
        # backend EMULATES bf16 (measured >10x on a whole phase-1 step:
        # 96+ min and counting vs ~7 min f32 at 2^20), so the scale
        # record pins the f32 policy explicitly.
        '--f32',
        '--aot_compile',
        '--obs-dir', obs_dir,
        '--supervise', '--max-restarts', '2',
        '--watchdog-deadline', str(args.watchdog),
    ]
    if row_shards > 1:
        argv += ['--row_shards', str(row_shards)]
    if args.obs_port is not None:
        # Live telemetry on the CLI child: with the default 0 each leg
        # binds its own free port and advertises it in heartbeat.json,
        # so the supervisor/aggregate discover it without coordination
        # (a fixed port would collide between the 8-dev and 1-dev legs).
        argv += ['--obs-port', str(args.obs_port)]
    return argv


def run_leg(args, name, row_shards, n_devices, n_s=None, e_s=None):
    obs_dir = os.path.join(args.workdir, f'obs_{name}')
    env = dict(
        os.environ,
        JAX_PLATFORMS='cpu',
        XLA_FLAGS=(os.environ.get('XLA_FLAGS', '')
                   + f' --xla_force_host_platform_device_count='
                     f'{n_devices}'),
        # The jax-0.4.37 persistent-cache + donation family (PR 3): scale
        # evidence must never come from a deserialized executable.
        JAX_ENABLE_COMPILATION_CACHE='false',
    )
    log_path = os.path.join(args.workdir, f'{name}.log')
    done = os.path.join(obs_dir, 'recovery.json')
    if args.reuse and os.path.exists(done) and json.load(
            open(done)).get('outcome') == 'completed':
        # Collect-only rerun: the leg already completed in this workdir;
        # its wall clock comes from the supervisor's attempt ledger.
        rc = 0
        wall = sum(a.get('end_time', 0.0) - a.get('start_time', 0.0)
                   for a in json.load(open(done)).get('attempts', []))
        print(f'# {name}: reusing completed leg in {obs_dir}', flush=True)
    else:
        t0 = time.time()
        with open(log_path, 'w') as log:
            rc = subprocess.run(
                cli_argv(args, obs_dir, row_shards, n_s=n_s, e_s=e_s),
                cwd=REPO, env=env, stdout=log,
                stderr=subprocess.STDOUT).returncode
        wall = time.time() - t0
    print(f'# {name}: rc={rc} wall={wall:.0f}s (log: {log_path})',
          flush=True)
    # A supervised run's telemetry lands in attempt_<k>/ subdirs; the
    # run's outcome is the FINAL attempt (obs.report binds the root the
    # same way).
    final_dir = obs_dir
    attempts = sorted(
        (d for d in os.listdir(obs_dir) if d.startswith('attempt_')),
        key=lambda d: int(d.split('_')[-1])) if os.path.isdir(obs_dir) \
        else []
    if attempts:
        final_dir = os.path.join(obs_dir, attempts[-1])
    if row_shards > 1:
        subprocess.run([sys.executable, '-m', 'dgmc_tpu.obs.aggregate',
                        final_dir], cwd=REPO, env=env,
                       stdout=subprocess.DEVNULL)
    report = {}
    try:
        out = subprocess.run([sys.executable, '-m', 'dgmc_tpu.obs.report',
                              obs_dir, '--json'], cwd=REPO, env=env,
                             capture_output=True, text=True)
        report = json.loads(out.stdout)
    except Exception as e:
        report = {'error': f'{type(e).__name__}: {e}'}
    recovery = {}
    rec_path = os.path.join(obs_dir, 'recovery.json')
    if os.path.exists(rec_path):
        with open(rec_path) as f:
            recovery = json.load(f)
    aot_memory = {}
    metrics_path = os.path.join(final_dir, 'metrics.jsonl')
    if os.path.exists(metrics_path):
        with open(metrics_path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                ev = str(rec.get('event', ''))
                if ev.startswith('aot_memory_'):
                    aot_memory[ev[len('aot_memory_'):]] = {
                        k: rec[k] for k in ('argument_bytes',
                                            'output_bytes', 'temp_bytes',
                                            'total_bytes') if k in rec}
    return {'rc': rc, 'wall_s': round(wall, 1), 'obs_dir': obs_dir,
            'report': report, 'recovery': recovery,
            'aot_memory': aot_memory,
            'hang_report': os.path.exists(
                os.path.join(obs_dir, 'hang_report.json'))}


def summarize(args, leg8, leg1):
    rep8, rep1 = leg8['report'], leg1['report']
    p50_8 = rep8.get('step_p50_s')
    p50_1 = rep1.get('step_p50_s')
    mem8 = leg8['aot_memory'].get('train_step', {})
    mem1 = leg1['aot_memory'].get('train_step', {})
    gib = 2 ** 30
    out = {
        'round': args.round,
        'metric': 'streamed_sharded_scale',
        'shape': (f'{args.nodes}x{args.nodes} k={args.k} '
                  f'chunk={args.chunk} block={args.block} '
                  f'dim={args.dim}'),
        'n_devices': args.devices,
        'mode': (f'supervised streamed-S synthetic KG alignment '
                 f'(dbp15k.py --synthetic --row_shards {args.devices} '
                 f'--stream_chunk {args.chunk} --aot_compile) under '
                 f'--supervise --watchdog-deadline {args.watchdog}'),
        'environment': {
            'platform': ('cpu (XLA --xla_force_host_platform_device_'
                         f'count={args.devices}; virtual devices on one '
                         'socket — machinery + memory evidence, not '
                         'parallel silicon)'),
        },
        'config': {
            'nodes': args.nodes, 'edges_s': args.edges,
            'edges_t': int(args.edges * 1.25), 'dim': args.dim,
            'psi_dim': args.psi_dim, 'rnd_dim': args.rnd_dim,
            'k': args.k, 'num_steps': args.num_steps,
            'epochs': args.epochs, 'phase1_epochs': args.phase1_epochs,
            'stream_chunk': args.chunk, 'topk_block': args.block,
            'seed': args.seed,
        },
        'supervision': {
            'outcome_8dev': leg8['recovery'].get('outcome'),
            'restarts_8dev': leg8['recovery'].get('restarts'),
            'outcome_1dev': leg1['recovery'].get('outcome'),
            'restarts_1dev': leg1['recovery'].get('restarts'),
            'hang_report': leg8['hang_report'] or leg1['hang_report'],
            'watchdog_deadline_s': args.watchdog,
        },
        'anchor_mode': (
            'weak-scaling slice: 1dev leg runs N_s/devices source rows '
            'against the full target set (equal per-device work)'
            if args.anchor == 'slice' else
            'strong: 1dev leg runs the full pair'),
        'timing': {
            'step_p50_ms_8dev': None if p50_8 is None
            else round(p50_8 * 1e3, 1),
            'step_p50_ms_1dev': None if p50_1 is None
            else round(p50_1 * 1e3, 1),
            'scaling_efficiency_vs_1dev': None
            if not (p50_8 and p50_1) else round(p50_1 / p50_8, 3),
            'per_device_step_skew_ratio': rep8.get(
                'skew', {}).get('step_time_ratio'),
            'devices_reporting': len(rep8.get('device_steps', {})),
            'wall_s_8dev': leg8['wall_s'], 'wall_s_1dev': leg1['wall_s'],
        },
        'memory': {
            'per_device_static_gib_8dev': None if not mem8 else round(
                mem8['total_bytes'] / gib, 3),
            'per_device_static_gib_1dev': None if not mem1 else round(
                mem1['total_bytes'] / gib, 3),
            'per_device_static_bytes_8dev': mem8 or None,
            'per_device_static_bytes_1dev': mem1 or None,
            'host_peak_rss_gib_8dev': None
            if not rep8.get('peak_memory_bytes') else round(
                rep8['peak_memory_bytes'] / gib, 3),
            'host_peak_rss_gib_1dev': None
            if not rep1.get('peak_memory_bytes') else round(
                rep1['peak_memory_bytes'] / gib, 3),
            'single_chip_flagship_peak_gib': 2.3,
        },
        'analysis': (
            'First million-entity (2^20 x 2^20) alignment smoke to '
            'complete end to end: the partition-rule streamed layout '
            '(S/shortlist/psi2-rows sharded over data, candidate search '
            'streamed per shard, AD-opaque) holds the refinement train '
            'step at ~1.0 GiB static per device — under the 15k x 20k '
            'single-chip flagship\'s 2.3 GiB live peak while the '
            'correspondence space is ~3,500x larger — and the full '
            'supervised two-phase train + eval schedule completed under '
            'the supervisor with zero restarts, no hang report, and '
            'device step skew 1.0. Timing on virtual CPU devices records '
            'machinery, not silicon: the weak-scaling anchor (one '
            'device\'s row slice against the full target set, run on 1 '
            'device) steps at 0.89x the 8-device full-pair step, i.e. '
            '~11% parallelization overhead from GSPMD collectives and '
            'shared-socket contention. The f32 policy is pinned because '
            'this CPU backend emulates bf16 (a whole phase-1 step '
            'measured >10x slower under the bf16 default). The '
            'real-accelerator rerun is a config change, not new code: '
            'the same partition rules on a TPU slice.'),
    }
    return out


def main(argv=None):
    parser = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    # 2^20 = 1,048,576 entities per side: >10^6, and divisible by every
    # mesh/chunk/block power of two in play.
    parser.add_argument('--nodes', type=int, default=1 << 20)
    parser.add_argument('--edges', type=int, default=1 << 22)
    parser.add_argument("--dim", type=int, default=16,
                        help='entity feature width (syn_dim)')
    parser.add_argument('--psi-dim', dest='psi_dim', type=int, default=16,
                        help='psi_1 width = candidate-search C')
    parser.add_argument('--rnd_dim', type=int, default=8)
    parser.add_argument('--k', type=int, default=10)
    parser.add_argument('--num-steps', dest='num_steps', type=int,
                        default=1)
    parser.add_argument('--epochs', type=int, default=2)
    parser.add_argument('--phase1-epochs', dest='phase1_epochs', type=int,
                        default=1)
    parser.add_argument('--chunk', type=int, default=2048)
    parser.add_argument('--block', type=int, default=8192,
                        help='candidate-search tile width for the scan '
                             'paths: the CPU-measured optimum at this '
                             'scale (the 256 library default is the '
                             'TPU-sweep number; on CPU the wider tile '
                             'amortizes the per-tile top_k pass)')
    parser.add_argument('--devices', type=int, default=8)
    parser.add_argument('--seed', type=int, default=7)
    parser.add_argument('--watchdog', type=int, default=7200)
    parser.add_argument('--obs-port', '--obs_port', dest='obs_port',
                        type=int, default=None, metavar='PORT',
                        help='arm the live telemetry plane on each CLI '
                             'leg (pass 0: every leg picks a free port '
                             'and advertises it in its heartbeat.json)')
    parser.add_argument('--round', type=int, default=7)
    parser.add_argument('--anchor', choices=['slice', 'full'],
                        default='slice',
                        help='1-device scaling anchor: "slice" = '
                             'weak-scaling (one device\'s row share, '
                             'full targets), "full" = the whole pair '
                             'on one device (~devices x the wall '
                             'clock)')
    parser.add_argument('--reuse', action='store_true',
                        help='skip any leg whose workdir obs dir '
                             'already holds a completed recovery.json '
                             '(collect-only rerun)')
    parser.add_argument('--workdir', type=str, default='/tmp/scale_bench')
    parser.add_argument('--out', type=str,
                        default=os.path.join(REPO, 'benchmarks',
                                             'SCALE_r07.json'))
    args = parser.parse_args(argv)
    os.makedirs(args.workdir, exist_ok=True)

    leg8 = run_leg(args, f'{args.devices}dev', args.devices, args.devices)
    if args.anchor == 'slice':
        # Weak-scaling anchor: the 1-device leg runs ONE device's share of
        # source rows (N_s / devices) against the FULL target set — the
        # per-device work of the sharded leg, so
        # t_1dev(slice) / t_Ndev(full) reads as weak-scaling efficiency.
        # The full 10^6-row single-device leg is ~devices x this wall
        # clock (~10 h on this container) for a number with the same
        # meaning; 'full' remains available for a real chip.
        leg1 = run_leg(args, '1dev', 0, 1,
                       n_s=args.nodes // args.devices,
                       e_s=args.edges // args.devices)
    else:
        leg1 = run_leg(args, '1dev', 0, 1)
    out = summarize(args, leg8, leg1)
    with open(args.out, 'w') as f:
        json.dump(out, f, indent=1)
        f.write('\n')
    print(json.dumps({k: out[k] for k in ('timing', 'memory',
                                          'supervision')}, indent=1))
    print(f'# wrote {args.out}')
    return 0 if (leg8['rc'] == 0 and leg1['rc'] == 0) else 1


if __name__ == '__main__':
    sys.exit(main())
