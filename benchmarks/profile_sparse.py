"""Profile the DBP15K-scale sparse step: per-kernel device-time attribution.

Wall-clock A/B runs on the shared tunneled chip vary +-15%; device-time
totals from a ``jax.profiler.trace`` don't (benchmarks/README.md). This
captures N steps, aggregates trace events on the device track, and maps
``fusion.NNN`` kernel names back to HLO ``op_name`` metadata from the
compiled executable so the totals are attributable to model stages.

Usage: python profile_sparse.py [--route] [--bf16] [--steps N]
"""

import argparse
import collections
import glob
import gzip
import json
import os
import re
import sys
import tempfile

import jax
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from timing import fence  # noqa: E402


def build_step(route=False, bf16=False):
    import bench
    from dgmc_tpu.models import DGMC, RelCNN
    from dgmc_tpu.train import create_train_state, make_train_step
    from dgmc_tpu.utils.data import PairBatch
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    gd = 'bfloat16' if bf16 else None   # match bench.py's legs exactly
    s = bench._kg_side(bench.SP_N_S, bench.SP_E_S, bench.SP_DIM, rng,
                       gather_dtype=gd)
    t = bench._kg_side(bench.SP_N_T, bench.SP_E_T, bench.SP_DIM, rng,
                       gather_dtype=gd)
    y = np.full((1, bench.SP_N_S), -1, np.int32)
    train_n = int(0.3 * bench.SP_N_S)
    y[0, :train_n] = rng.permutation(bench.SP_N_T)[:train_n]
    batch = jax.device_put(PairBatch(s=s, t=t, y=y, y_mask=y >= 0))
    jax.block_until_ready(batch)

    dt = jnp.bfloat16 if bf16 else None
    psi_1 = RelCNN(bench.SP_DIM, 256, num_layers=3, dropout=0.5, dtype=dt)
    psi_2 = RelCNN(32, 32, num_layers=3, dtype=dt)
    model = DGMC(psi_1, psi_2, num_steps=10, k=bench.SP_K,
                 topk_block=bench.SP_TOPK_BLOCK, route_sparse=route,
                 dtype=dt)
    tiny = PairBatch(s=bench._kg_side(32, 64, bench.SP_DIM, rng),
                     t=bench._kg_side(32, 64, bench.SP_DIM, rng),
                     y=np.zeros((1, 32), np.int32),
                     y_mask=np.ones((1, 32), bool))
    state = create_train_state(model, jax.random.key(0), tiny,
                               learning_rate=1e-3)
    step = make_train_step(model, loss_on_s0=False)
    compiled = bench._aot_compile(step, state, batch, jax.random.key(1))
    return compiled, state, batch


def hlo_opname_map(compiled):
    """Instruction name -> ``op_name`` metadata string, from the compiled
    HLO text. Kernels whose metadata only exists on a called computation's
    body (not the fusion root line) stay unmapped and fall into the
    ``other`` rollup bucket — acceptable for this diagnostic."""
    mapping = {}
    for line in compiled.as_text().splitlines():
        name = re.match(r'\s*%?([\w\.\-]+)\s*=', line)
        op = re.search(r'op_name="([^"]+)"', line)
        if name and op:
            mapping.setdefault(name.group(1), op.group(1))
    return mapping


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--route', action='store_true')
    ap.add_argument('--bf16', action='store_true')
    ap.add_argument('--steps', type=int, default=10)
    ap.add_argument('--json', default=None,
                    help='dump the full kernel table to this path')
    args = ap.parse_args()

    compiled, state, batch = build_step(route=args.route, bf16=args.bf16)
    key = jax.random.key(1)
    for _ in range(2):
        key, sub = jax.random.split(key)
        state, out = compiled(state, batch, sub)
    fence(out['loss'])

    tmp = tempfile.mkdtemp(prefix='sparse_trace_')
    with jax.profiler.trace(tmp):
        for _ in range(args.steps):
            key, sub = jax.random.split(key)
            state, out = compiled(state, batch, sub)
        fence(out['loss'])

    files = glob.glob(os.path.join(tmp, '**', '*.trace.json.gz'),
                      recursive=True)
    assert files, f'no trace file under {tmp}'
    with gzip.open(sorted(files)[-1], 'rt') as f:
        trace = json.load(f)

    events = trace['traceEvents']
    # Device tracks: pick pids whose process name mentions TPU / device.
    pid_names = {e['pid']: e['args'].get('name', '')
                 for e in events
                 if e.get('ph') == 'M' and e.get('name') == 'process_name'
                 and 'args' in e}
    dev_pids = {p for p, n in pid_names.items()
                if 'TPU' in n or 'Device' in n or '/device' in n.lower()}
    if not dev_pids:  # fall back: every pid that has X events with dur
        dev_pids = {e['pid'] for e in events if e.get('ph') == 'X'}

    totals = collections.Counter()
    counts = collections.Counter()
    ops = {}
    for e in events:
        if e.get('ph') != 'X' or e.get('pid') not in dev_pids:
            continue
        name = e.get('name', '?')
        # Skip module-level spans (the whole jitted program and bare
        # step-number aggregates) — they double-count their kernels.
        if re.match(r'^\d+$', name) or name.startswith('jit_'):
            continue
        totals[name] += e.get('dur', 0)
        counts[name] += 1
        if isinstance(e.get('args'), dict):
            long = e['args'].get('long_name') or e['args'].get('tf_op', '')
            if long:
                ops.setdefault(name, long)

    opmap = hlo_opname_map(compiled)
    total_us = sum(totals.values())
    print(f'# device total: {total_us / 1e3 / args.steps:.1f} ms/step '
          f'across {len(totals)} kernel names '
          f'({sum(counts.values()) / args.steps:.0f} kernel launches/step)')
    print(f'{"ms/step":>8}  {"calls":>6}  kernel  [op_name]')
    for name, us in totals.most_common(40):
        op = opmap.get(name.split('.(')[0], '')
        print(f'{us / 1e3 / args.steps:8.2f}  '
              f'{counts[name] / args.steps:6.1f}  {name[:60]}  '
              f'[{op[:80]}]')

    # Stage-level rollup from op_name paths when available.
    stage = collections.Counter()
    stage_n = collections.Counter()
    for name, us in totals.items():
        op = ops.get(name, '') + ' ' + opmap.get(name.split('.(')[0], '')
        low = (op + ' ' + name).lower()
        direction = 'bwd' if 'transpose(jvp' in low else 'fwd'
        for pat in ('psi_1', 'psi_2', 'topk', 'scatter-add', 'adam',
                    'take_along_axis', 'corr_route', 'softmax'):
            if pat in low:
                stage[f'{direction}:{pat}'] += us
                stage_n[f'{direction}:{pat}'] += counts[name]
                break
        else:
            stage[f'{direction}:other'] += us
            stage_n[f'{direction}:other'] += counts[name]
    print('\n# rollup (ms/step, launches/step):')
    for k, us in stage.most_common():
        print(f'  {k:20s} {us / 1e3 / args.steps:8.2f} '
              f'{stage_n[k] / args.steps:8.1f}')

    if args.json:
        with open(args.json, 'w') as f:
            json.dump([{'name': n, 'op': ops.get(n, ''),
                        'hlo': opmap.get(n.split('.(')[0], ''),
                        'us': us, 'calls': counts[n]}
                       for n, us in totals.most_common()], f)
        print(f'# full table -> {args.json}')


if __name__ == '__main__':
    main()
