"""Race exact top-k engines at DBP15K scale (15k x 20k, k=10).

Three candidates, all with semantics identical to ``dense_topk`` including
tie order (lower target index wins on equal scores):

- ``sort``: the current scan — concat carry + full score tile, one
  ``lax.top_k`` over ``block + k`` per tile (sorts the whole tile).
- ``tilesort``: per-tile ``lax.top_k`` down to k, then a tiny merge of
  ``2k`` with the carry.
- ``itermax``: k rounds of (argmax, mask) per tile — O(k·block) VPU work
  instead of a sort — then the same tiny merge.

Writes ``benchmarks/topk_tpu.json`` with ms/call for each engine x block
size; the winner becomes ``chunked_topk``'s implementation.
"""

import functools
import json
import os

import jax
import jax.numpy as jnp
import numpy as np

import sys
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from timing import best_of, fence  # noqa: E402

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   'topk_tpu.json')

N_S, N_T, C, K = 15000, 20000, 256, 10
ITERS = 10


def _prep(h_s, h_t, t_mask, block):
    B = h_s.shape[0]
    N_t = h_t.shape[1]
    if t_mask is None:
        t_mask = jnp.ones((B, N_t), dtype=bool)
    pad = (-N_t) % block
    if pad:
        h_t = jnp.pad(h_t, ((0, 0), (0, pad), (0, 0)))
        t_mask = jnp.pad(t_mask, ((0, 0), (0, pad)))
    nb = h_t.shape[1] // block
    C_ = h_t.shape[2]
    ht_b = h_t.reshape(B, nb, block, C_).transpose(1, 0, 2, 3)
    m_b = t_mask.reshape(B, nb, block).transpose(1, 0, 2)
    starts = jnp.arange(nb, dtype=jnp.int32) * block
    return ht_b, m_b, starts


def _merge(vals, idx, tile_vals, tile_idx, k):
    """Merge carry (k, sorted) with a tile's top-k (sorted): carry first so
    earlier blocks win ties, exactly like one top_k over the union."""
    all_vals = jnp.concatenate([vals, tile_vals], axis=-1)
    all_idx = jnp.concatenate([idx, tile_idx], axis=-1)
    new_vals, pos = jax.lax.top_k(all_vals, k)
    return new_vals, jnp.take_along_axis(all_idx, pos, axis=-1)


@functools.partial(jax.jit, static_argnames=('k', 'block'))
def topk_sort(h_s, h_t, k, t_mask=None, block=1024):
    B, N_s, _ = h_s.shape
    ht_b, m_b, starts = _prep(h_s, h_t, t_mask, block)
    neg = jnp.finfo(h_s.dtype).min

    def step(carry, inp):
        vals, idx = carry
        ht, m, start = inp
        scores = jnp.einsum('bsc,btc->bst', h_s, ht)
        scores = jnp.where(m[:, None, :], scores, neg)
        cand = jnp.broadcast_to(start + jnp.arange(block, dtype=jnp.int32),
                                scores.shape)
        av = jnp.concatenate([vals, scores], axis=-1)
        ai = jnp.concatenate([idx, cand], axis=-1)
        nv, pos = jax.lax.top_k(av, k)
        return (nv, jnp.take_along_axis(ai, pos, axis=-1)), None

    init = (jnp.full((B, N_s, k), -jnp.inf, h_s.dtype),
            jnp.zeros((B, N_s, k), jnp.int32))
    (vals, idx), _ = jax.lax.scan(step, init, (ht_b, m_b, starts))
    return idx


@functools.partial(jax.jit, static_argnames=('k', 'block'))
def topk_tilesort(h_s, h_t, k, t_mask=None, block=1024):
    B, N_s, _ = h_s.shape
    ht_b, m_b, starts = _prep(h_s, h_t, t_mask, block)
    neg = jnp.finfo(h_s.dtype).min
    kk = min(k, block)

    def step(carry, inp):
        vals, idx = carry
        ht, m, start = inp
        scores = jnp.einsum('bsc,btc->bst', h_s, ht)
        scores = jnp.where(m[:, None, :], scores, neg)
        tv, tp = jax.lax.top_k(scores, kk)       # tile-local, idx-asc ties
        ti = start + tp.astype(jnp.int32)
        return _merge(vals, idx, tv, ti, k), None

    init = (jnp.full((B, N_s, k), -jnp.inf, h_s.dtype),
            jnp.zeros((B, N_s, k), jnp.int32))
    (vals, idx), _ = jax.lax.scan(step, init, (ht_b, m_b, starts))
    return idx


def _itermax(scores, start, k):
    """k rounds of (argmax, mask-out). argmax takes the first maximum, so
    ties resolve to the lowest index — the lax.top_k rule."""
    block = scores.shape[-1]
    cols = jnp.arange(block, dtype=jnp.int32)
    neg_inf = -jnp.inf

    def one(s, _):
        p = jnp.argmax(s, axis=-1)
        v = jnp.take_along_axis(s, p[..., None], axis=-1)[..., 0]
        s = jnp.where(cols == p[..., None], neg_inf, s)
        return s, (v, p)

    _, (tv, tp) = jax.lax.scan(one, scores, None, length=k)
    tv = jnp.moveaxis(tv, 0, -1)                # [B, N_s, k]
    tp = jnp.moveaxis(tp, 0, -1)
    return tv, start + tp.astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=('k', 'block'))
def topk_itermax(h_s, h_t, k, t_mask=None, block=1024):
    B, N_s, _ = h_s.shape
    ht_b, m_b, starts = _prep(h_s, h_t, t_mask, block)
    neg = jnp.finfo(h_s.dtype).min

    def step(carry, inp):
        vals, idx = carry
        ht, m, start = inp
        scores = jnp.einsum('bsc,btc->bst', h_s, ht)
        scores = jnp.where(m[:, None, :], scores, neg)
        tv, ti = _itermax(scores, start, min(k, block))
        return _merge(vals, idx, tv, ti, k), None

    init = (jnp.full((B, N_s, k), -jnp.inf, h_s.dtype),
            jnp.zeros((B, N_s, k), jnp.int32))
    (vals, idx), _ = jax.lax.scan(step, init, (ht_b, m_b, starts))
    return idx


ENGINES = {'sort': topk_sort, 'tilesort': topk_tilesort,
           'itermax': topk_itermax}


def main():
    rng = np.random.RandomState(0)
    h_s = jnp.asarray(rng.randn(1, N_S, C).astype(np.float32))
    h_t = jnp.asarray(rng.randn(1, N_T, C).astype(np.float32))

    # Correctness gate first (tiny, with ties, on whatever backend).
    hs_small = jnp.asarray(rng.randint(0, 3, (2, 17, 8)).astype(np.float32))
    ht_small = jnp.asarray(rng.randint(0, 3, (2, 23, 8)).astype(np.float32))
    mask = jnp.asarray(rng.rand(2, 23) > 0.2)
    dense = jnp.einsum('bsc,btc->bst', hs_small, ht_small)
    dense = jnp.where(mask[:, None, :], dense,
                      jnp.finfo(jnp.float32).min)
    want = jax.lax.top_k(dense, 5)[1]
    for name, fn in ENGINES.items():
        got = fn(hs_small, ht_small, 5, t_mask=mask, block=8)
        assert np.array_equal(np.asarray(got), np.asarray(want)), name
    print('correctness (incl. ties): all engines match dense_topk')

    results = {}
    for name, fn in ENGINES.items():
        results[name] = {}
        for block in (1024, 2048, 4096):
            f = lambda: fn(h_s, h_t, K, block=block)
            fence(f()[0, 0, 0])  # compile + fence

            def window(f=f):
                out = None
                for _ in range(ITERS):
                    out = f()
                fence(out[0, 0, 0])

            ms = best_of(window) / ITERS * 1e3
            results[name][str(block)] = round(ms, 2)
            print(f'{name} block={block}: {ms:.1f} ms')

    with open(OUT, 'w') as f:
        json.dump({'device': str(jax.devices()[0].device_kind),
                   'shape': f'{N_S}x{N_T} C={C} k={K}',
                   'ms': results}, f, indent=1)
    print(f'wrote {OUT}')


if __name__ == '__main__':
    main()
