"""Locate the dense-workload bottleneck on the real chip.

bench.py measures ~330 train pairs/sec for the dense flagship (batch 128,
64 nodes, 10 consensus steps) — ~1% of the chip's nominal FLOPs. This
script decomposes a step: dispatch+fence floor, forward vs train,
consensus-step count scaling, and single- vs multi-step-per-dispatch, to
tell tunnel overhead apart from on-chip inefficiency.
"""

import os
import sys

import jax
import jax.numpy as jnp


from timing import best_of, fence  # noqa: E402


def main():
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench

    # 1. Dispatch + fence floor: a trivial jitted add, fetched.
    f = jax.jit(lambda a, b: a + b)
    x = jnp.ones(()); y = jnp.ones(())
    fence(f(x, y))
    n = 50
    dt = best_of(lambda: [fence(f(x, y)) for _ in range(n)])
    print(f'dispatch+fence round-trip: {dt / n * 1e3:.2f} ms')

    # Async pipelining: N dispatches, one fence.
    def pipelined():
        out = x
        for _ in range(n):
            out = f(out, y)
        fence(out)
    dt = best_of(pipelined)
    print(f'pipelined dispatch: {dt / n * 1e3:.2f} ms/call')

    state, step, batch = bench.build_dense()
    key = jax.random.key(1)

    def run_steps(num):
        # The step donates its input state; thread it across windows.
        nonlocal_state = run_steps.state
        k = run_steps.key
        out = None
        for _ in range(num):
            k, sub = jax.random.split(k)
            nonlocal_state, out = step(nonlocal_state, batch, sub)
        fence(out['loss'])
        run_steps.state, run_steps.key = nonlocal_state, k

    run_steps.state, run_steps.key = state, key
    run_steps(3)  # warmup/compile
    dt = best_of(lambda: run_steps(10))
    print(f'train step (10 consensus): {dt / 10 * 1e3:.1f} ms')
    state = run_steps.state

    # Forward-only at eval (no grad, no optimizer).
    from dgmc_tpu.train import make_eval_step
    from dgmc_tpu.models import DGMC, SplineCNN
    psi_1 = SplineCNN(1, 256, dim=2, num_layers=2, cat=False, lin=True,
                      dropout=0.0)
    psi_2 = SplineCNN(64, 64, dim=2, num_layers=2, cat=True, lin=True)
    for steps in (0, 10):
        model = DGMC(psi_1, psi_2, num_steps=steps, k=-1)
        ev = make_eval_step(model)
        fence(ev(state, batch, key)['count'])
        dt = best_of(lambda: [fence(ev(state, batch, key)['count'])
                              for _ in range(10)])
        print(f'eval fwd num_steps={steps}: {dt / 10 * 1e3:.1f} ms')

    # Train with num_steps=0 (psi_1 + S_0 loss only).
    from dgmc_tpu.train import make_train_step
    model0 = DGMC(psi_1, psi_2, num_steps=0, k=-1)
    step0 = make_train_step(model0, loss_on_s0=True)
    st0 = state
    k0 = key
    for _ in range(2):
        k0, sub = jax.random.split(k0)
        st0, out = step0(st0, batch, sub)
    fence(out['loss'])

    def run0():
        nonlocal st0, k0
        out = None
        for _ in range(10):
            k0, sub = jax.random.split(k0)
            st0, out = step0(st0, batch, sub)
        fence(out['loss'])
    dt = best_of(run0)
    print(f'train step (0 consensus): {dt / 10 * 1e3:.1f} ms')


if __name__ == '__main__':
    main()
