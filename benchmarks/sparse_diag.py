"""Decompose the DBP15K-scale sparse training step (bench.py: ~473 ms).

Components: candidate search (Pallas top-k, ~21 ms), psi_1 RelCNN at
15k/20k nodes, 10 consensus iterations (scatter r_t, psi_2, gather,
MLP), loss/optimizer. Uses long fenced windows (the tunnel fence costs
~120 ms, so short windows lie — see benchmarks/dense_diag.py).
"""

import os
import sys

import jax
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

from timing import best_of, fence  # noqa: E402


def main():
    import bench
    from dgmc_tpu.models import DGMC, RelCNN
    from dgmc_tpu.train import create_train_state, make_train_step
    from dgmc_tpu.utils.data import PairBatch

    rng = np.random.RandomState(0)
    s = bench._kg_side(bench.SP_N_S, bench.SP_E_S, bench.SP_DIM, rng)
    t = bench._kg_side(bench.SP_N_T, bench.SP_E_T, bench.SP_DIM, rng)
    y = np.full((1, bench.SP_N_S), -1, np.int32)
    train_n = int(0.3 * bench.SP_N_S)
    y[0, :train_n] = rng.permutation(bench.SP_N_T)[:train_n]
    batch = jax.device_put(PairBatch(s=s, t=t, y=y, y_mask=y >= 0))
    jax.block_until_ready(batch)

    tiny = PairBatch(s=bench._kg_side(32, 64, bench.SP_DIM, rng),
                     t=bench._kg_side(32, 64, bench.SP_DIM, rng),
                     y=np.zeros((1, 32), np.int32),
                     y_mask=np.ones((1, 32), bool))

    def run_config(label, num_steps, iters=10):
        psi_1 = RelCNN(bench.SP_DIM, 256, num_layers=3, dropout=0.5)
        psi_2 = RelCNN(32, 32, num_layers=3)
        model = DGMC(psi_1, psi_2, num_steps=num_steps, k=bench.SP_K,
                     topk_block=bench.SP_TOPK_BLOCK)
        state = create_train_state(model, jax.random.key(0), tiny,
                                   learning_rate=1e-3)
        step = make_train_step(model, loss_on_s0=False)
        key = jax.random.key(1)
        for _ in range(2):
            key, sub = jax.random.split(key)
            state, out = step(state, batch, sub)
        fence(out['loss'])

        def window():
            nonlocal state, key
            out = None
            for _ in range(iters):
                key, sub = jax.random.split(key)
                state, out = step(state, batch, sub)
            fence(out['loss'])
        ms = best_of(window) / iters * 1e3
        print(f'{label}: {ms:.1f} ms/step')
        return ms

    full = run_config('full step (10 consensus)', 10)
    zero = run_config('no consensus (psi_1 + topk + loss)', 0)
    one = run_config('1 consensus iteration', 1)
    print(f'-> per consensus iteration: {(full - zero) / 10:.1f} ms '
          f'(check vs 1-step delta {one - zero:.1f} ms)')
    print(f'-> psi_1 + topk + loss + optimizer: {zero:.1f} ms')


if __name__ == '__main__':
    main()
