"""Shared measurement harness for every script in this directory.

The methodology is load-bearing for all numbers recorded in the
checked-in JSONs: on the tunneled TPU platform ``block_until_ready``
intermittently returns before execution finishes, so every timed window is
fenced by a scalar device-to-host fetch (which cannot lie), and the
reported figure is the best of several windows because the chip is shared
and effective speed varies with external load. A fenced round trip costs
~120 ms here, so short windows overstate per-call cost — amortize over
enough iterations (see dense_diag.py findings).
"""

import time


def fence(x):
    """Force completion by fetching one scalar to the host."""
    return float(x)


def best_of(run, windows=3):
    """Minimum wall-clock seconds of ``run()`` over several windows."""
    best = float('inf')
    for _ in range(windows):
        t0 = time.perf_counter()
        run()
        best = min(best, time.perf_counter() - t0)
    return best


def best_ms_per_iter(make_run, iters, windows=3):
    """ms/iteration for a ``make_run(iters)`` callable, best of windows."""
    return best_of(lambda: make_run(iters), windows) / iters * 1e3
