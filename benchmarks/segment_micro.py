"""Probe the TPU cost model of the graph primitive ops.

Times gather (take_along_axis) and segment_sum at DBP15K-like sizes,
varying table size, update count, width, sortedness, and the
indices_are_sorted/unique hints — to find which formulation the rest of
the framework should standardize on.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from timing import best_of, fence  # noqa: E402


def timeit(name, f, *args):
    f = jax.jit(f)
    out = f(*args)
    fence(out.ravel()[0])

    def window():
        o = None
        for _ in range(30):
            o = f(*args)
        fence(o.ravel()[0])
    ms = best_of(window) / 30 * 1e3
    print(f'{name:48s}: {ms:6.2f} ms')


def main():
    rng = np.random.RandomState(0)
    for n, e, c in ((20000, 120000, 32), (35000, 220000, 32),
                    (20000, 120000, 256)):
        print(f'--- N={n} E={e} C={c} ---')
        x = jnp.asarray(rng.randn(n, c).astype(np.float32))
        xb = x[None]
        idx = jnp.asarray(rng.randint(0, n, e).astype(np.int32))
        idx_sorted = jnp.sort(idx)
        msgs = jnp.asarray(rng.randn(e, c).astype(np.float32))

        timeit('gather take_along_axis [1,N,C]',
               lambda xb, i: jnp.take_along_axis(xb, i[None, :, None],
                                                 axis=1), xb, idx)
        timeit('gather x[idx] flat', lambda x, i: x[i], x, idx)
        timeit('segment_sum unsorted',
               lambda m, i: jax.ops.segment_sum(m, i, num_segments=n),
               msgs, idx)
        timeit('segment_sum sorted (no hint)',
               lambda m, i: jax.ops.segment_sum(m, i, num_segments=n),
               msgs, idx_sorted)
        timeit('segment_sum sorted + hint',
               lambda m, i: jax.ops.segment_sum(m, i, num_segments=n,
                                                indices_are_sorted=True),
               msgs, idx_sorted)
        timeit('segment_sum vmap B=1 unsorted',
               lambda m, i: jax.vmap(lambda mm, ii: jax.ops.segment_sum(
                   mm, ii, num_segments=n))(m[None], i[None]),
               msgs, idx)


if __name__ == '__main__':
    main()
