"""Execute the WILLOW transfer protocol END TO END on the real chip.

The smoke tests run 1-2 epochs of this harness; this script runs the whole
L5 protocol (reference ``examples/willow.py:143-174``) at reduced scale:
VOC pretrain (full 15 epochs) -> snapshot -> ``--runs`` independent runs x
15 epochs each with a fresh Adam -> mean ± std — on fixture-format data
(the environment has no egress; random-VGG features) so the evidence is
about the HARNESS executing its full protocol on-chip, wall-clock
included, not about reproducing the paper number (that needs the real
datasets + converted VGG weights, EXPERIMENTS.md).

Usage: python benchmarks/willow_protocol.py [--runs 5] [--out runs/...]
"""

import argparse
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))


def build_fixture_data(root, seed=0):
    """VOC + WILLOW trees in the published layouts (Berkeley XML with
    height/width visible_bounds; WILLOW .mat pts_coord [2, 10]).

    Keypoints are PER-CATEGORY PROTOTYPE layouts plus small jitter — like
    real object classes, keypoint i sits in a consistent geometric
    neighborhood across instances, so identity matching is learnable from
    graph structure alone (no images ship: features come from a VGG
    forward over zeros, so the signal is the Delaunay geometry — the
    protocol evidence is the harness TRAINING to above-chance accuracy,
    not reproducing the paper's numbers, which need the real datasets)."""
    from scipy.io import savemat
    from dgmc_tpu.datasets.pascal_voc import CATEGORIES
    from dgmc_tpu.datasets.willow import _DIRNAMES
    rng = np.random.RandomState(seed)
    voc = os.path.join(root, 'voc')
    willow = os.path.join(root, 'willow')
    kp_names = ['a', 'b', 'c', 'd', 'e', 'f', 'g', 'h']
    for cat in CATEGORIES:
        ann = os.path.join(voc, 'annotations', cat)
        os.makedirs(ann, exist_ok=True)
        proto = rng.rand(len(kp_names), 2) * 80 + 10
        for i in range(8):
            pts = np.clip(proto + rng.randn(len(kp_names), 2) * 2.5,
                          1.0, 99.0)
            kps = '\n'.join(
                f'<keypoint name="{n}" x="{pts[j, 0]:.2f}" '
                f'y="{pts[j, 1]:.2f}" visible="1" z="0"/>'
                for j, n in enumerate(kp_names))
            # A few 2007 images in car/motorbike: the protocol filters them
            # out of pretraining (reference willow.py:28-31).
            year = 2007 if (cat in ('car', 'motorbike') and i < 2) else 2008
            with open(os.path.join(ann, f'{year}_{i:06d}.xml'), 'w') as f:
                f.write(f'<annotation><image>{year}_{i:06d}</image>'
                        f'<visible_bounds height="90" width="90" xmin="5" '
                        f'ymin="5"/><keypoints>{kps}</keypoints>'
                        f'</annotation>')
    for dirname in _DIRNAMES.values():
        base = os.path.join(willow, 'WILLOW-ObjectClass', dirname)
        os.makedirs(base, exist_ok=True)
        proto = rng.rand(2, 10) * 100
        for i in range(30):
            savemat(os.path.join(base, f'im{i:03d}.mat'),
                    {'pts_coord': np.clip(proto + rng.randn(2, 10) * 2.5,
                                          0.0, 100.0)})
    return voc, willow


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--runs', type=int, default=5)
    ap.add_argument('--pre_epochs', type=int, default=15)
    ap.add_argument('--epochs', type=int, default=15)
    ap.add_argument('--dim', type=int, default=256)
    ap.add_argument('--rnd_dim', type=int, default=128)
    ap.add_argument('--out', default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        'runs', 'willow_protocol_r05.jsonl'))
    ap.add_argument('--root', default=None,
                    help='persistent fixture root: reused if it already '
                         'exists, so the cached VGG features survive '
                         'retries on a flaky tunnel (default: fresh tmp)')
    args = ap.parse_args()

    if args.root:
        root = args.root
        # Reuse only a COMPLETE fixture (sentinel written after a full
        # build): a retry after a mid-build crash, or a root built by an
        # older generator, must rebuild rather than silently hand
        # willow.main a partial/stale tree.
        sentinel = os.path.join(root, '.fixture_complete_v2')
        if os.path.exists(sentinel):
            voc = os.path.join(root, 'voc')
            willow_root = os.path.join(root, 'willow')
        else:
            import shutil
            for sub in ('voc', 'willow'):
                shutil.rmtree(os.path.join(root, sub), ignore_errors=True)
            os.makedirs(root, exist_ok=True)
            voc, willow_root = build_fixture_data(root)
            open(sentinel, 'w').close()
    else:
        root = tempfile.mkdtemp(prefix='willow_protocol_')
        voc, willow_root = build_fixture_data(root)

    from dgmc_tpu.experiments import willow
    t0 = time.time()
    accs = willow.main([
        '--voc_root', voc, '--willow_root', willow_root,
        '--vgg_weights', 'random',
        '--dim', str(args.dim), '--rnd_dim', str(args.rnd_dim),
        '--num_layers', '2', '--num_steps', '10',
        '--batch_size', '64', '--pre_epochs', str(args.pre_epochs),
        '--epochs', str(args.epochs), '--runs', str(args.runs),
        '--test_samples', '100',
        '--metrics_log', args.out,
    ])
    wall = time.time() - t0
    print(f'# full protocol wall-clock: {wall:.1f}s '
          f'({args.pre_epochs} pre-epochs + {args.runs} runs x '
          f'{args.epochs} epochs)')
    print('# mean per category over runs:',
          np.asarray(accs).mean(axis=0).round(2).tolist())
    print('# std  per category over runs:',
          np.asarray(accs).std(axis=0).round(2).tolist())


if __name__ == '__main__':
    main()
