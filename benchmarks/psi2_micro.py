"""Micro-benchmark: RelCNN psi_2 forward+backward, separate vs unioned.

Probes why merging the per-step psi_2 pair applications changed the
DBP15K-scale consensus iteration cost (benchmarks/sparse_diag.py).
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from timing import best_of, fence  # noqa: E402


def main():
    import bench
    from dgmc_tpu.models import RelCNN
    from dgmc_tpu.ops.graph import pair_apply, union_pair_graphs

    rng = np.random.RandomState(0)
    g_s = jax.device_put(bench._kg_side(bench.SP_N_S, bench.SP_E_S, 32, rng))
    g_t = jax.device_put(bench._kg_side(bench.SP_N_T, bench.SP_E_T, 32, rng))
    g_u = jax.device_put(union_pair_graphs(g_s, g_t))
    jax.block_until_ready((g_s, g_t, g_u))

    psi = RelCNN(32, 32, num_layers=3)
    params = psi.init(jax.random.PRNGKey(0), g_s.x, g_s)

    def sep_loss(p, xs, xt):
        os_ = psi.apply(p, xs, g_s)
        ot_ = psi.apply(p, xt, g_t)
        return os_.sum() + ot_.sum()

    def uni_loss(p, xs, xt):
        os_, ot_ = pair_apply(lambda x, g: psi.apply(p, x, g), g_u, xs, xt)
        return os_.sum() + ot_.sum()

    xs, xt = g_s.x, g_t.x
    for name, fn in (('separate', sep_loss), ('union', uni_loss)):
        for mode, f in (('fwd', jax.jit(fn)),
                        ('fwd+bwd', jax.jit(jax.grad(fn)))):
            out = f(params, xs, xt)
            fence(jax.tree_util.tree_leaves(out)[0].ravel()[0])

            def window(f=f):
                out = None
                for _ in range(20):
                    out = f(params, xs, xt)
                fence(jax.tree_util.tree_leaves(out)[0].ravel()[0])
            ms = best_of(window) / 20 * 1e3
            print(f'{name:9s} {mode:8s}: {ms:6.2f} ms')


if __name__ == '__main__':
    main()
