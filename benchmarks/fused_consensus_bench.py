"""On-chip measurement: fused (Pallas) vs unfused dense consensus update.

The dense consensus step materializes ``D = o_s[:, :, None] - o_t[:, None]``
of shape ``[B, N_s, N_t, R]`` (reference ``dgmc/models/dgmc.py:178``) — R
times the correspondence matrix. The Pallas kernel
(``dgmc_tpu/ops/pallas/consensus.py``) forms D tile-by-tile in VMEM instead.
This script measures both paths (forward + backward, the training shape of
the computation) across sizes from comfortably-fitting to memory-bound, and
writes ``benchmarks/fused_consensus_tpu.json`` — the recorded evidence
behind the size-dispatch threshold in ``dgmc_tpu/models/dgmc.py``.

Run on the real chip: ``python benchmarks/fused_consensus_bench.py``.
"""

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

from dgmc_tpu.ops.pallas.consensus import (consensus_update,
                                           consensus_update_reference)

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from timing import best_of, fence  # noqa: E402

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   'fused_consensus_tpu.json')

# (B, N, R): D-tensor sizes 64 MB -> 8.6 GB.
SIZES = [
    (8, 256, 32),
    (1, 1024, 64),
    (1, 2048, 128),
    (1, 4096, 128),
]
ITERS = 10


def measure(fn, *args):
    """Best-of-3 windows of ITERS forward+backward steps; returns ms/step.
    A scalar fetch fences each window (block_until_ready is unreliable on
    the tunneled platform, see bench.py)."""
    grad = jax.jit(jax.grad(
        lambda o_s, o_t, w1, b1, w2, b2:
            fn(o_s, o_t, w1, b1, w2, b2).sum(), argnums=(0, 1, 2)))
    out = grad(*args)
    fence(out[0].sum())  # compile + fence

    def window():
        out = None
        for _ in range(ITERS):
            out = grad(*args)
        fence(out[0].sum())

    return best_of(window) / ITERS * 1e3


def peak_hbm():
    stats = jax.local_devices()[0].memory_stats() or {}
    return stats.get('peak_bytes_in_use')


def main():
    assert jax.default_backend() == 'tpu', 'measure on the real chip'
    results = []
    for B, N, R in SIZES:
        rng = np.random.RandomState(0)
        o_s = jnp.asarray(rng.randn(B, N, R).astype(np.float32))
        o_t = jnp.asarray(rng.randn(B, N, R).astype(np.float32))
        w1 = jnp.asarray(rng.randn(R, R).astype(np.float32) / np.sqrt(R))
        b1 = jnp.zeros((R,), jnp.float32)
        w2 = jnp.asarray(rng.randn(R, 1).astype(np.float32) / np.sqrt(R))
        b2 = jnp.zeros((1,), jnp.float32)
        d_gib = B * N * N * R * 4 / 2**30

        entry = {'B': B, 'N': N, 'R': R, 'D_gib': round(d_gib, 3)}
        try:
            entry['unfused_ms'] = round(
                measure(consensus_update_reference,
                        o_s, o_t, w1, b1, w2, b2), 2)
        except Exception as e:
            entry['unfused_ms'] = None
            entry['unfused_error'] = f'{type(e).__name__}: {e}'[:200]
        try:
            entry['fused_ms'] = round(
                measure(lambda *a: consensus_update(*a, False),
                        o_s, o_t, w1, b1, w2, b2), 2)
        except Exception as e:
            entry['fused_ms'] = None
            entry['fused_error'] = f'{type(e).__name__}: {e}'[:200]
        entry['peak_hbm_gib_so_far'] = (
            round(peak_hbm() / 2**30, 2) if peak_hbm() else None)
        results.append(entry)
        print(json.dumps(entry))

    with open(OUT, 'w') as f:
        json.dump({'device': str(jax.devices()[0].device_kind),
                   'iters': ITERS, 'results': results}, f, indent=1)
    print(f'wrote {OUT}')


if __name__ == '__main__':
    main()
