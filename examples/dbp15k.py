"""Launcher for the DBP15K workload (reference
``examples/dbp15k.py``).

The implementation lives in :mod:`dgmc_tpu.experiments.dbp15k`; after
``pip install -e .`` it is also available as the ``dgmc-dbp15k`` console
script. The repo root is put first on ``sys.path`` so the checkout always
wins over any stale installed copy.
"""

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dgmc_tpu.experiments.dbp15k import main, parse_args  # noqa: E402,F401

if __name__ == '__main__':
    main()
