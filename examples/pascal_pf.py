"""Launcher for the PascalPF zero-shot workload (reference
``examples/pascal_pf.py``).

The implementation lives in :mod:`dgmc_tpu.experiments.pascal_pf`; after
``pip install -e .`` it is also available as the ``dgmc-pascal-pf`` console
script. The repo root is put first on ``sys.path`` so the checkout always
wins over any stale installed copy.
"""

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dgmc_tpu.experiments.pascal_pf import main, parse_args  # noqa: E402,F401

if __name__ == '__main__':
    main()
