"""Launcher for the WILLOW transfer workload (reference
``examples/willow.py``).

The implementation lives in :mod:`dgmc_tpu.experiments.willow`; after
``pip install -e .`` it is also available as the ``dgmc-willow`` console
script. The repo root is put first on ``sys.path`` so the checkout always
wins over any stale installed copy.
"""

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dgmc_tpu.experiments.willow import main, parse_args  # noqa: E402,F401

if __name__ == '__main__':
    main()
