"""Generate the README/docs example correspondence figure.

Trains the flagship dense matcher briefly on synthetic geometric pairs
(the pascal_pf protocol, reference ``examples/pascal_pf.py:23-65``) and
renders one unseen pair's predicted matches with
``dgmc_tpu.utils.viz.plot_matches``.

Run:  python docs/make_example_figure.py
Writes: docs/source/_static/example_matches.png
"""

import os

import numpy as np
import sys

import jax

jax.config.update('jax_platforms', 'cpu')

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))


def main():
    import matplotlib
    matplotlib.use('Agg')
    import matplotlib.pyplot as plt

    from dgmc_tpu.data import (Cartesian, Compose, Constant, KNNGraph,
                               RandomGraphPairs)
    from dgmc_tpu.models import DGMC, SplineCNN
    from dgmc_tpu.train import (create_train_state, make_eval_step,
                                make_train_step)
    from dgmc_tpu.utils import PairLoader
    from dgmc_tpu.utils.viz import plot_matches, predicted_targets

    transform = Compose([Constant(), KNNGraph(k=8), Cartesian()])
    ds = RandomGraphPairs(min_inliers=20, max_inliers=30, min_outliers=0,
                          max_outliers=2, transform=transform, length=64,
                          seed=0)
    loader = PairLoader(ds, 16, shuffle=True, seed=0,
                        num_nodes=36, num_edges=300)

    model = DGMC(SplineCNN(1, 128, dim=2, num_layers=2, cat=False),
                 SplineCNN(32, 32, dim=2, num_layers=2, cat=True),
                 num_steps=3, k=-1)
    batch0 = next(iter(loader))
    state = create_train_state(model, jax.random.key(0), batch0,
                               learning_rate=1e-3)
    step = make_train_step(model, loss_on_s0=True)
    key = jax.random.key(1)
    for epoch in range(20):
        ds.set_epoch(epoch)
        for batch in loader:
            key, sub = jax.random.split(key)
            state, _ = step(state, batch, sub)

    from dgmc_tpu.utils.data import pad_pair_batch

    eval_ds = RandomGraphPairs(min_inliers=20, max_inliers=30,
                               min_outliers=0, max_outliers=2,
                               transform=transform, length=16, seed=123)
    pair = eval_ds[0]              # host Graphs carry the 2D keypoints
    batch = pad_pair_batch([pair], 36, 300)
    key, k1 = jax.random.split(key)
    _, S_L = model.apply({'params': state.params}, batch.s, batch.t,
                         rngs={'noise': k1})
    pred = predicted_targets(S_L)

    b = 0
    n_s, n_t = pair.s.pos.shape[0], pair.t.pos.shape[0]
    ax = plot_matches(
        pair.s.pos, pair.t.pos, pred[b][:n_s],
        y=np.asarray(batch.y[b][:n_s]),
        edges_s=np.stack([pair.s.edge_index[0], pair.s.edge_index[1]], 1),
        edges_t=np.stack([pair.t.edge_index[0], pair.t.edge_index[1]], 1))
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       'source', '_static', 'example_matches.png')
    os.makedirs(os.path.dirname(out), exist_ok=True)
    ax.figure.savefig(out, dpi=120, bbox_inches='tight')
    print(f'wrote {out}')


if __name__ == '__main__':
    main()
