"""Sphinx configuration — counterpart of the reference's docs build
(reference ``docs/source/conf.py``; CI hook at ``.travis.yml:37-38``)."""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join('..', '..')))

import dgmc_tpu  # noqa: E402

project = 'dgmc_tpu'
author = 'dgmc_tpu developers'
release = dgmc_tpu.__version__

extensions = [
    'sphinx.ext.autodoc',
    'sphinx.ext.napoleon',
    'sphinx.ext.viewcode',
]

autodoc_member_order = 'bysource'
# jax/flax/optax/orbax are heavyweight; docs build imports the real ones
# when available (CI installs the package), and these mocks keep the build
# alive in minimal environments.
autodoc_mock_imports = []

html_theme = 'alabaster'
exclude_patterns = []
